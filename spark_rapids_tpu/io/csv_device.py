"""Device-side CSV numeric parsing.

Reference parity: the reference parses CSV ON the accelerator — the host
reads line-aligned chunks and cudf tokenizes + converts on device
(GpuBatchScanExec.scala:322-520, device parse under the semaphore at
:474-502). The TPU-native split keeps the same control/data-plane shape as
the parquet device decoder (io/parquet_device.py):

- HOST (control plane, vectorized numpy): one pass over the raw bytes to
  find field boundaries (separator/newline positions -> a (rows, cols)
  offset table). No value is converted on the host.
- DEVICE (data plane): raw bytes + per-field (start, len) upload once; a
  jitted kernel gathers up to MAXW bytes per field and folds digits into
  int64 — the conversion FLOPs happen on the accelerator.

Scope: integral columns (INT8..INT64); DATE (strict ISO YYYY-MM-DD) and
TIMESTAMP (ISO date[ T]HH:MM:SS[.f{1,6}]<zone>, zone required — the host
oracle reads timestamp[us, tz=UTC]) columns; and —
where the backend has f64 — FLOAT32/FLOAT64 columns with plain decimal
literals (sign, digits, one dot; <= 15 significant digits and <= 22
fractional digits, so the single f64 division is correctly rounded and
bit-identical to the host parser; exponents/inf/nan take the host path).
Quoted fields are handled
structurally (quote-aware boundary scan + quote stripping; escaped ""
unescapes via a host control-plane rewrite before upload). Regular
column count per line. Empty fields are NULL
(pyarrow's strings_can_be_null oracle behavior); malformed digits abandon
the device path for the split so both engines behave identically.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.dtypes import DataType

MAXW = 20   # int64: up to 19 digits + sign
MAXW_F = 24  # float: sign + 15 digits + dot (+ slack)

_NL = 0x0A
_CR = 0x0D
_QUOTE = 0x22
_MINUS = 0x2D
_PLUS = 0x2B
_ZERO = 0x30

INTEGRAL = (DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64)
FLOATS = (DataType.FLOAT32, DataType.FLOAT64)
_DOT = 0x2E


class FieldTable:
    """Host-side field offset table for one CSV file."""

    __slots__ = ("raw", "starts", "lens", "num_rows", "header_names",
                 "_dev_raw")

    def __init__(self, raw, starts, lens, num_rows, header_names):
        self.raw = raw              # np.uint8 [nbytes]
        self.starts = starts        # np.int32 [rows, cols]
        self.lens = lens            # np.int32 [rows, cols]
        self.num_rows = num_rows
        self.header_names = header_names  # list[str] | None
        self._dev_raw = None

    def device_raw(self):
        """The raw bytes on device — uploaded once per file, shared by
        every column decode."""
        if self._dev_raw is None:
            self._dev_raw = jnp.asarray(self.raw)
        return self._dev_raw


def plan_fields(data: bytes, ncols: int, header: bool,
                sep: str = ",") -> Optional[FieldTable]:
    """Field-boundary scan (native single-pass when built, numpy multi-pass
    fallback). None -> structure too complex for the device path (quotes,
    ragged rows): caller host-falls-back."""
    if not data or len(data) > 2 ** 31 - 2:
        return None
    sep_b = ord(sep)
    if sep_b in (_NL, _CR, _QUOTE):
        return None
    if b'"' in data:
        # quote-aware boundary scan lives only in the numpy path
        res = _plan_fields_quoted(data, ncols, sep_b)
    else:
        res = _plan_fields_native(data, ncols, sep_b)
        if res is NotImplemented:
            res = _plan_fields_py(data, ncols, sep_b)
    if res is None:
        return None
    arr, starts, lens, n_lines = res
    return _finish_plan(data, arr, starts, lens, n_lines, ncols, header)


def _plan_fields_native(data: bytes, ncols: int, sep_b: int):
    """Single native sweep (srt_csv_plan). NotImplemented -> no library."""
    import ctypes

    from spark_rapids_tpu.native import get_lib

    lib = get_lib()
    if lib is None:
        return NotImplemented
    est = data.count(b"\n") + (0 if data.endswith(b"\n") else 1)
    if est <= 0:
        est = 1
    starts = np.empty(est * ncols, dtype=np.int32)
    lens = np.empty(est * ncols, dtype=np.int32)
    rc = lib.srt_csv_plan(
        data, len(data), sep_b, ncols,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), est)
    if rc < 0:
        return None
    n_lines = int(rc)
    arr = np.frombuffer(data, dtype=np.uint8)
    return (arr, starts[:n_lines * ncols].reshape(n_lines, ncols),
            lens[:n_lines * ncols].reshape(n_lines, ncols), n_lines)


def _plan_fields_quoted(data: bytes, ncols: int, sep_b: int):
    """Quote-aware boundary scan (reference: cudf's quoted-field tokenizer
    behind GpuBatchScanExec.scala:322-520). Separators/newlines inside
    quotes are not boundaries; fully-quoted fields strip their quotes;
    escaped "" pairs inside quoted fields unescape via a host rewrite
    (second quote of each pair deleted, spans remapped to the rewritten
    buffer). Stray unpaired quotes -> None (host fallback)."""
    arr = np.frombuffer(data, dtype=np.uint8)
    is_q = arr == _QUOTE
    # inside[i]: byte i lies inside a quoted section (after an odd number
    # of quotes). A quote toggles state AFTER itself.
    inside = (np.cumsum(is_q) - is_q) % 2 == 1
    is_bound = ((arr == sep_b) | (arr == _NL)) & ~inside & ~is_q
    bpos = np.flatnonzero(is_bound).astype(np.int64)
    if arr[-1] != _NL:
        bpos = np.append(bpos, len(arr))
    n_fields = len(bpos)
    if n_fields % ncols != 0:
        return None
    n_lines = n_fields // ncols
    ends = bpos.reshape(n_lines, ncols)
    interior = ends[:, :-1].ravel()
    if interior.size and (arr[interior] == _NL).any():
        return None
    line_final = ends[:, -1]
    real = line_final[line_final < len(arr)]
    if real.size and (arr[real] != _NL).any():
        return None
    starts = np.empty_like(ends)
    starts[:, 0] = np.concatenate(([0], ends[:-1, -1] + 1))
    starts[:, 1:] = ends[:, :-1] + 1
    lens = ends - starts
    last_ends = ends[:, -1]
    has_cr = np.zeros(n_lines, dtype=bool)
    nonempty = lens[:, -1] > 0
    prev = np.clip(last_ends - 1, 0, len(arr) - 1)
    has_cr[nonempty] = arr[prev[nonempty]] == _CR
    lens[:, -1] -= has_cr.astype(np.int32)
    # strip full surrounding quotes; any other quote layout -> fallback
    fs = starts.ravel()
    fl = lens.ravel()
    first_q = np.zeros(fs.shape, dtype=bool)
    last_q = np.zeros(fs.shape, dtype=bool)
    nz = fl >= 2
    first_q[nz] = arr[fs[nz]] == _QUOTE
    last_q[nz] = arr[np.clip(fs[nz] + fl[nz] - 1, 0,
                             len(arr) - 1)] == _QUOTE
    quoted = first_q & last_q
    # escaped "" pairs inside quoted fields: the first quote of a pair is
    # seen while the pre-state is INSIDE (the toggle math already kept
    # boundaries correct across the zero-width out-in flip)
    pre_inside = inside
    nxt_q = np.zeros_like(is_q)
    nxt_q[:-1] = is_q[1:]
    pair_first = is_q & nxt_q & pre_inside
    # per-field quote / escape-pair counts via cum-count differences
    qcum = np.concatenate(([0], np.cumsum(is_q)))
    ecum = np.concatenate(([0], np.cumsum(pair_first)))
    lo = np.clip(fs, 0, len(arr))
    hi = np.clip(fs + fl, 0, len(arr))
    qcnt = qcum[hi] - qcum[lo]
    ecnt = ecum[hi] - ecum[lo]
    # quoted fields: outer pair + every interior quote in an escape pair;
    # bare fields: no quotes at all. Anything else -> host fallback.
    if not np.all((quoted & (qcnt == 2 + 2 * ecnt))
                  | (~quoted & (qcnt == 0))):
        return None
    fs = fs + quoted.astype(np.int64)
    fl = fl - 2 * quoted.astype(np.int64)
    if pair_first.any():
        # unescape: delete the SECOND quote of each pair and remap spans
        # (host control-plane rewrite, mirroring cudf's unescape pass)
        second = np.zeros_like(pair_first)
        second[1:] = pair_first[:-1]
        delcum = np.concatenate(([0], np.cumsum(second)))
        fl = fl - (delcum[np.clip(fs + fl, 0, len(arr))]
                   - delcum[np.clip(fs, 0, len(arr))])
        fs = fs - delcum[np.clip(fs, 0, len(arr))]
        arr = arr[~second]
    return (arr, fs.reshape(n_lines, ncols).astype(np.int64),
            fl.reshape(n_lines, ncols).astype(np.int64), n_lines)


def _plan_fields_py(data: bytes, ncols: int, sep_b: int):
    """Vectorized numpy fallback for srt_csv_plan."""
    arr = np.frombuffer(data, dtype=np.uint8)
    if (arr == _QUOTE).any():
        return None
    is_bound = (arr == sep_b) | (arr == _NL)
    bpos = np.flatnonzero(is_bound).astype(np.int64)
    # virtual trailing newline when the file doesn't end with one
    if arr[-1] != _NL:
        bpos = np.append(bpos, len(arr))
    n_fields = len(bpos)
    if n_fields % ncols != 0:
        return None
    n_lines = n_fields // ncols
    ends = bpos.reshape(n_lines, ncols)
    # every line's last boundary must be a newline (or the virtual EOF one),
    # and no interior boundary may be a newline — else the reshape is wrong
    interior = ends[:, :-1].ravel()
    if interior.size and (arr[interior] == _NL).any():
        return None
    # ...and every line-final boundary must be a newline (the last may be
    # the virtual EOF boundary)
    line_final = ends[:, -1]
    real = line_final[line_final < len(arr)]
    if real.size and (arr[real] != _NL).any():
        return None
    starts = np.empty_like(ends)
    starts[:, 0] = np.concatenate(([0], ends[:-1, -1] + 1))
    starts[:, 1:] = ends[:, :-1] + 1
    lens = ends - starts
    # tolerate CRLF: trim a trailing \r from the last field of each line
    last_ends = ends[:, -1]
    has_cr = np.zeros(n_lines, dtype=bool)
    nonempty = lens[:, -1] > 0
    prev = np.clip(last_ends - 1, 0, len(arr) - 1)
    has_cr[nonempty] = arr[prev[nonempty]] == _CR
    lens[:, -1] -= has_cr.astype(np.int32)
    return arr, starts, lens, n_lines


def _finish_plan(data: bytes, arr, starts, lens, n_lines: int, ncols: int,
                 header: bool) -> Optional[FieldTable]:
    if ncols == 1:
        # blank lines are SKIPPED lines, not NULL rows (pyarrow's
        # ignore_empty_lines oracle behavior); only reachable for
        # single-column files — a blank line is ragged otherwise
        keep = lens[:, 0] > 0
        if header and n_lines >= 1:
            keep[0] = True  # never drop the header row
        if not keep.all():
            starts = starts[keep]
            lens = lens[keep]
            n_lines = int(keep.sum())
    header_names = None
    if header:
        if n_lines < 1:
            return None
        # slice from `arr`, not `data`: the quoted planner's unescape pass
        # may have rewritten the buffer and remapped starts/lens to it
        header_names = [
            bytes(arr[starts[0, j]:starts[0, j] + lens[0, j]]).decode(
                "utf-8", errors="replace").strip()
            for j in range(ncols)]
        starts = starts[1:]
        lens = lens[1:]
        n_lines -= 1
    return FieldTable(arr, np.ascontiguousarray(starts, dtype=np.int32),
                      np.ascontiguousarray(lens, dtype=np.int32),
                      n_lines, header_names)


@functools.partial(jax.jit, static_argnums=(3,))
def _parse_int_kernel(raw, starts, lens, maxw: int):
    """Fold up to `maxw` gathered bytes per field into int64. Returns
    (values, validity, malformed): empty fields are NULL; anything else the
    strict grammar ('-' then digits, in int64 range — what the pyarrow host
    oracle accepts) does not cover is MALFORMED, and the caller abandons the
    device path for the whole split so both engines raise identically."""
    idx = starts[:, None].astype(jnp.int32) + \
        jnp.arange(maxw, dtype=jnp.int32)[None, :]
    ch = raw[jnp.clip(idx, 0, raw.shape[0] - 1)]
    inb = jnp.arange(maxw, dtype=jnp.int32)[None, :] < lens[:, None]
    ch = jnp.where(inb, ch, 0)
    first = ch[:, 0]
    neg = first == _MINUS
    skip = neg.astype(jnp.int32)  # '+' is malformed, matching pyarrow
    digits = ch.astype(jnp.int32) - _ZERO
    isdig = (digits >= 0) & (digits <= 9)
    pos = jnp.arange(maxw, dtype=jnp.int32)[None, :]
    digpos = (pos >= skip[:, None]) & inb
    all_digits = jnp.all(jnp.where(digpos, isdig, True), axis=1)
    ndig = lens - skip
    ok = all_digits & (ndig > 0) & (lens <= maxw)
    val = jnp.zeros(starts.shape[0], dtype=jnp.int64)
    imax = jnp.int64(np.iinfo(np.int64).max)
    overflow = jnp.zeros(starts.shape[0], dtype=bool)
    for i in range(maxw):
        d = jnp.where(isdig[:, i], digits[:, i], 0).astype(jnp.int64)
        # detect BEFORE the fold can wrap: val*10 + d > int64max
        overflow = overflow | (digpos[:, i] & (val > (imax - d) // 10))
        val = jnp.where(digpos[:, i], val * 10 + d, val)
    val = jnp.where(neg, -val, val)
    nonempty = lens > 0
    validity = ok & nonempty & ~overflow
    malformed = nonempty & ~validity
    return jnp.where(validity, val, 0), validity, malformed


@functools.partial(jax.jit, static_argnums=(3,))
def _parse_float_kernel(raw, starts, lens, maxw: int):
    """Plain decimal floats: [-] digits [. digits], <= 15 significant
    digits and <= 22 fractional digits. The value is mantissa / 10^scale in
    ONE f64 division — both operands are exact, so the result is the
    correctly-rounded double of the literal, bit-identical to the host
    parser. Exponents / inf / nan / longer literals are MALFORMED (the
    caller host-falls-back for the split; the host parses them fine)."""
    idx = starts[:, None].astype(jnp.int32) + \
        jnp.arange(maxw, dtype=jnp.int32)[None, :]
    ch = raw[jnp.clip(idx, 0, raw.shape[0] - 1)]
    inb = jnp.arange(maxw, dtype=jnp.int32)[None, :] < lens[:, None]
    ch = jnp.where(inb, ch, 0)
    neg = ch[:, 0] == _MINUS
    skip = neg.astype(jnp.int32)
    digits = ch.astype(jnp.int32) - _ZERO
    isdig = (digits >= 0) & (digits <= 9)
    isdot = ch == _DOT
    pos = jnp.arange(maxw, dtype=jnp.int32)[None, :]
    body = (pos >= skip[:, None]) & inb
    # exactly 0 or 1 dots; everything else in the body must be a digit
    ndots = jnp.sum((body & isdot).astype(jnp.int32), axis=1)
    ok_chars = jnp.all(jnp.where(body, isdig | isdot, True), axis=1)
    dotpos = jnp.argmax(body & isdot, axis=1).astype(jnp.int32)
    has_dot = ndots == 1
    # fractional digit count; mantissa = all digits folded in order
    frac = jnp.where(has_dot, lens - 1 - dotpos, 0)
    ndig = lens - skip - has_dot.astype(jnp.int32)
    m = jnp.zeros(starts.shape[0], dtype=jnp.int64)
    for i in range(maxw):
        d = jnp.where(isdig[:, i], digits[:, i], 0).astype(jnp.int64)
        m = jnp.where(body[:, i] & isdig[:, i], m * 10 + d, m)
    ok = ok_chars & (ndots <= 1) & (ndig > 0) & (ndig <= 15) & \
        (frac >= 0) & (frac <= 22) & (lens <= maxw)
    p10 = jnp.asarray([10.0 ** k for k in range(23)], dtype=jnp.float64)
    val = m.astype(jnp.float64) / p10[jnp.clip(frac, 0, 22)]
    val = jnp.where(neg, -val, val)
    nonempty = lens > 0
    validity = ok & nonempty
    malformed = nonempty & ~validity
    return jnp.where(validity, val, 0.0), validity, malformed


def decode_float_column(table: FieldTable, col_idx: int, dtype: DataType,
                        cap: int):
    """Parse one float column on device, padded to `cap` rows (same
    contract as decode_int_column)."""
    from spark_rapids_tpu.columnar.batch import physical_np_dtype

    n = table.num_rows
    starts = np.zeros(cap, dtype=np.int32)
    lens = np.zeros(cap, dtype=np.int32)
    starts[:n] = table.starts[:, col_idx]
    lens[:n] = table.lens[:, col_idx]
    row_mask = jnp.arange(cap) < n
    val, validity, malformed = _parse_float_kernel(table.device_raw(),
                                                   jnp.asarray(starts),
                                                   jnp.asarray(lens),
                                                   MAXW_F)
    malformed = malformed & row_mask
    npdt = physical_np_dtype(dtype)
    if npdt != np.dtype(np.float64):
        val = val.astype(npdt)
    return val, validity & row_mask, jnp.any(malformed)


def decode_int_column(table: FieldTable, col_idx: int, dtype: DataType,
                      cap: int):
    """Parse one integral column on device, padded to `cap` rows. Returns
    (data, validity, any_malformed) where any_malformed is a DEVICE bool
    scalar — the caller batches the malformed checks of every column into
    ONE host sync (each sync is a network round trip when the chip is
    tunneled) and falls back to the host parser if any is set, so both
    engines raise the same error on bad fields."""
    from spark_rapids_tpu.columnar.batch import physical_np_dtype

    n = table.num_rows
    starts = np.zeros(cap, dtype=np.int32)
    lens = np.zeros(cap, dtype=np.int32)
    starts[:n] = table.starts[:, col_idx]
    lens[:n] = table.lens[:, col_idx]
    row_mask = jnp.arange(cap) < n
    val, validity, malformed = _parse_int_kernel(table.device_raw(),
                                                 jnp.asarray(starts),
                                                 jnp.asarray(lens), MAXW)
    malformed = malformed & row_mask
    npdt = physical_np_dtype(dtype)
    if npdt != np.dtype(np.int64):
        info = np.iinfo(npdt)
        in_range = (val >= info.min) & (val <= info.max)
        malformed = malformed | (validity & ~in_range & row_mask)
        val = jnp.where(in_range, val, 0).astype(npdt)
    return val, validity & row_mask, jnp.any(malformed)


@functools.partial(jax.jit, static_argnums=(3,))
def _parse_date_kernel(raw, starts, lens, maxw: int):
    """Strict ISO 'YYYY-MM-DD' (what the pyarrow host oracle accepts for
    date32) -> epoch days on device. Invalid layouts AND invalid civil
    dates (2023-02-30) are MALFORMED -> the caller abandons the device path
    so the host parser raises the identical error."""
    from spark_rapids_tpu.ops import datetimeops as DT

    idx = starts[:, None].astype(jnp.int32) + \
        jnp.arange(maxw, dtype=jnp.int32)[None, :]
    ch = raw[jnp.clip(idx, 0, raw.shape[0] - 1)]
    inb = jnp.arange(maxw, dtype=jnp.int32)[None, :] < lens[:, None]
    ch = jnp.where(inb, ch, 0)
    digits = ch.astype(jnp.int32) - _ZERO
    isdig = (digits >= 0) & (digits <= 9)
    layout = jnp.ones(starts.shape[0], dtype=bool)
    for i in (0, 1, 2, 3, 5, 6, 8, 9):
        layout = layout & isdig[:, i]
    layout = layout & (ch[:, 4] == _MINUS) & (ch[:, 7] == _MINUS)
    layout = layout & (lens == 10)
    y = (digits[:, 0] * 1000 + digits[:, 1] * 100
         + digits[:, 2] * 10 + digits[:, 3])
    m = digits[:, 5] * 10 + digits[:, 6]
    d = digits[:, 8] * 10 + digits[:, 9]
    days = DT.days_from_civil(jnp, y, m, d)
    ry, rm, rd = DT.civil_from_days(jnp, days)
    civil_ok = (ry == y) & (rm == m) & (rd == d)
    nonempty = lens > 0
    validity = layout & civil_ok & nonempty
    malformed = nonempty & ~validity
    return (jnp.where(validity, days, 0).astype(jnp.int32), validity,
            malformed)


@functools.partial(jax.jit, static_argnums=(3,))
def _parse_timestamp_kernel(raw, starts, lens, maxw: int):
    """ISO zoned timestamps on device:
    'YYYY-MM-DD[ T]HH:MM:SS[.f{1,6}]<zone>' with zone = 'Z' | ±HH |
    ±HHMM | ±HH:MM -> epoch micros. The host oracle reads TIMESTAMP CSV
    columns as arrow timestamp[us, tz=UTC], which REQUIRES a zone offset
    in the text — naive timestamps are a conversion error there, so here
    they are MALFORMED (whole split -> host, which raises identically)."""
    from spark_rapids_tpu.ops import datetimeops as DT

    n = starts.shape[0]
    idx = starts[:, None].astype(jnp.int32) + \
        jnp.arange(maxw, dtype=jnp.int32)[None, :]
    ch = raw[jnp.clip(idx, 0, raw.shape[0] - 1)]
    inb = jnp.arange(maxw, dtype=jnp.int32)[None, :] < lens[:, None]
    ch = jnp.where(inb, ch, 0)
    digits = ch.astype(jnp.int32) - _ZERO
    isdig = (digits >= 0) & (digits <= 9)
    date_ok = lens >= 19
    for i in (0, 1, 2, 3, 5, 6, 8, 9):
        date_ok = date_ok & isdig[:, i]
    date_ok = date_ok & (ch[:, 4] == _MINUS) & (ch[:, 7] == _MINUS)
    y = (digits[:, 0] * 1000 + digits[:, 1] * 100
         + digits[:, 2] * 10 + digits[:, 3])
    m = digits[:, 5] * 10 + digits[:, 6]
    d = digits[:, 8] * 10 + digits[:, 9]
    days = DT.days_from_civil(jnp, y, m, d)
    ry, rm, rd = DT.civil_from_days(jnp, days)
    civil_ok = (ry == y) & (rm == m) & (rd == d)

    time_ok = jnp.ones(n, dtype=bool)
    for i in (11, 12, 14, 15, 17, 18):
        time_ok = time_ok & isdig[:, i]
    sep = ch[:, 10]
    time_ok = time_ok & ((sep == 0x20) | (sep == 0x54))  # ' ' | 'T'
    time_ok = time_ok & (ch[:, 13] == 0x3A) & (ch[:, 16] == 0x3A)
    hh = digits[:, 11] * 10 + digits[:, 12]
    mi = digits[:, 14] * 10 + digits[:, 15]
    ss = digits[:, 17] * 10 + digits[:, 18]
    time_ok = time_ok & (hh < 24) & (mi < 60) & (ss < 60)

    # fraction: optional '.' at 19 followed by a 1..6-digit run
    has_dot = (lens > 19) & (ch[:, 19] == _DOT)
    fd = jnp.zeros(n, jnp.int32)
    going = has_dot
    frac = jnp.zeros(n, dtype=jnp.int64)
    for i in range(6):
        p = 20 + i
        going = going & (jnp.int32(p) < lens) & isdig[:, p]
        fd = fd + going.astype(jnp.int32)
        frac = jnp.where(going, frac * 10 + digits[:, p], frac)
    frac_ok = ~has_dot | (fd >= 1)
    p10 = jnp.asarray([10 ** k for k in range(7)], dtype=jnp.int64)
    frac = frac * p10[jnp.clip(6 - fd, 0, 6)]

    # zone suffix starts right after seconds or fraction
    zstart = jnp.where(has_dot, 20 + fd, 19)
    zlen = lens - zstart

    def at(k):
        pos = jnp.clip(zstart + k, 0, maxw - 1)
        v = jnp.take_along_axis(ch, pos[:, None], axis=1)[:, 0]
        return jnp.where(zstart + k < lens, v, 0).astype(jnp.int32)

    def dg(k):
        return at(k) - _ZERO

    def isd(k):
        v = dg(k)
        return (v >= 0) & (v <= 9)

    sign_ch = at(0)
    signed = (sign_ch == _PLUS) | (sign_ch == _MINUS)
    z_utc = (zlen == 1) & (at(0) == 0x5A)  # 'Z'
    z_hh = (zlen == 3) & signed & isd(1) & isd(2)
    z_hhmm = (zlen == 5) & signed & isd(1) & isd(2) & isd(3) & isd(4)
    z_colon = (zlen == 6) & signed & isd(1) & isd(2) & (at(3) == 0x3A) \
        & isd(4) & isd(5)
    off_h = dg(1) * 10 + dg(2)
    off_m = jnp.where(z_hhmm, dg(3) * 10 + dg(4),
                      jnp.where(z_colon, dg(4) * 10 + dg(5), 0))
    zone_ok = z_utc | ((z_hh | z_hhmm | z_colon)
                       & (off_h < 24) & (off_m < 60))
    off_us = jnp.where(z_utc, 0,
                       (off_h * 3600 + off_m * 60).astype(jnp.int64)
                       * 1_000_000)
    off_us = jnp.where(sign_ch == _MINUS, -off_us, off_us)

    ok = date_ok & civil_ok & time_ok & frac_ok & zone_ok
    us = (days.astype(jnp.int64) * 86_400_000_000
          + (hh * 3600 + mi * 60 + ss).astype(jnp.int64) * 1_000_000
          + frac - off_us)
    nonempty = lens > 0
    validity = ok & nonempty
    malformed = nonempty & ~validity
    return jnp.where(validity, us, 0), validity, malformed


MAXW_TS = 32  # 19 + .ffffff (7) + ±HH:MM (6)


def _decode_with_kernel(kernel, maxw: int, table: FieldTable, col_idx: int,
                        cap: int):
    """Shared (starts, lens) padding + row/malformed masking around a
    field-parse kernel (same contract as decode_int_column)."""
    n = table.num_rows
    starts = np.zeros(cap, dtype=np.int32)
    lens = np.zeros(cap, dtype=np.int32)
    starts[:n] = table.starts[:, col_idx]
    lens[:n] = table.lens[:, col_idx]
    row_mask = jnp.arange(cap) < n
    val, validity, malformed = kernel(table.device_raw(),
                                      jnp.asarray(starts),
                                      jnp.asarray(lens), maxw)
    return val, validity & row_mask, jnp.any(malformed & row_mask)


def decode_date_column(table: FieldTable, col_idx: int, cap: int):
    return _decode_with_kernel(_parse_date_kernel, 10, table, col_idx, cap)


def decode_timestamp_column(table: FieldTable, col_idx: int, cap: int):
    return _decode_with_kernel(_parse_timestamp_kernel, MAXW_TS, table,
                               col_idx, cap)


def _null_sentinels() -> List[bytes]:
    """pyarrow's default CSV null spellings, read at runtime so the device
    path can never drift from the host oracle's list (the boundary scan
    strips quotes, and quoted sentinels are null too —
    quoted_strings_can_be_null defaults True)."""
    global _NULL_SENTINELS
    if _NULL_SENTINELS is None:
        import pyarrow.csv as pc

        _NULL_SENTINELS = [s.encode() for s in
                           pc.ConvertOptions().null_values if s]
    return _NULL_SENTINELS


_NULL_SENTINELS: Optional[List[bytes]] = None


@functools.partial(jax.jit, static_argnums=(3,))
def _match_sentinels_kernel(raw, starts, lens, sentinels: Tuple[bytes, ...]):
    """Per field: does it equal any null sentinel? (Empty fields are handled
    by the caller — lens == 0.)"""
    smax = max(len(s) for s in sentinels)
    idx = starts[:, None].astype(jnp.int32) + \
        jnp.arange(smax, dtype=jnp.int32)[None, :]
    ch = raw[jnp.clip(idx, 0, raw.shape[0] - 1)]
    inb = jnp.arange(smax, dtype=jnp.int32)[None, :] < lens[:, None]
    ch = jnp.where(inb, ch, 0)
    is_null = jnp.zeros(starts.shape[0], dtype=bool)
    for s in sentinels:
        pat = jnp.asarray(np.frombuffer(s.ljust(smax, b"\0"), np.uint8))
        is_null = is_null | ((lens == len(s)) &
                             jnp.all(ch == pat[None, :], axis=1))
    return is_null


def decode_string_column(table: FieldTable, col_idx: int, cap: int):
    """Build a device STRING column straight from the boundary plan: the
    (start, len) tables plus the already-uploaded raw bytes ARE the column —
    one fused gather packs the field bytes contiguously (reference: cudf
    parses the full CSV type matrix on device, GpuBatchScanExec.scala:
    322-520). Null semantics match the host oracle's strings_can_be_null
    list via an on-device sentinel match. Returns a ColumnVector; total
    byte size is host-known, so there is no device sync."""
    from spark_rapids_tpu.columnar.batch import (
        ColumnVector,
        bucket_capacity,
    )
    from spark_rapids_tpu.columnar.strings import build_from_plan

    n = table.num_rows
    starts = np.zeros(cap, dtype=np.int32)
    lens = np.zeros(cap, dtype=np.int32)
    starts[:n] = table.starts[:, col_idx]
    lens[:n] = table.lens[:, col_idx]
    total = int(lens.astype(np.int64).sum())
    raw = table.device_raw()
    dstarts = jnp.asarray(starts)
    dlens = jnp.asarray(lens)
    row_mask = jnp.arange(cap) < n
    is_null = _match_sentinels_kernel(raw, dstarts, dlens,
                                      tuple(_null_sentinels()))
    validity = row_mask & (dlens > 0) & ~is_null
    out_len = jnp.where(validity, dlens, 0)
    byte_cap = bucket_capacity(max(total, 8))
    out_bytes, offsets = build_from_plan(
        [raw], jnp.zeros((cap,), jnp.int32), dstarts, out_len, byte_cap)
    return ColumnVector(DataType.STRING, out_bytes, validity, offsets)


def device_parseable(dtype: DataType) -> bool:
    if dtype in INTEGRAL:
        return True
    if dtype is DataType.STRING:
        return True
    if dtype in (DataType.DATE, DataType.TIMESTAMP):
        return True
    if dtype is DataType.FLOAT64:
        # the exact-rounding argument needs a real f64 division on device.
        # FLOAT32 stays on the host: parse-f64-then-narrow double-rounds,
        # which can differ from Arrow's direct decimal->float32 conversion
        # on midpoint-adjacent literals.
        from spark_rapids_tpu.columnar.batch import device_float64_supported

        return device_float64_supported()
    return False


def decode_column(table: FieldTable, col_idx: int, dtype: DataType,
                  cap: int):
    if dtype in FLOATS:
        return decode_float_column(table, col_idx, dtype, cap)
    if dtype is DataType.DATE:
        return decode_date_column(table, col_idx, cap)
    if dtype is DataType.TIMESTAMP:
        return decode_timestamp_column(table, col_idx, cap)
    return decode_int_column(table, col_idx, dtype, cap)


def eligible_attrs(attrs, header_names: Optional[List[str]],
                   attr_names_in_file_order: List[str]) -> dict:
    """Map attr name -> file column index for device-parseable columns."""
    order = header_names if header_names is not None \
        else attr_names_in_file_order
    out = {}
    for a in attrs:
        if device_parseable(a.data_type) and a.name in order:
            out[a.name] = order.index(a.name)
    return out
