"""Device-side parquet column decode.

Reference parity: the reference decodes parquet ON the accelerator —
it reassembles a minimal in-memory file from raw column chunks on the host
and hands the bytes to the GPU decoder (`GpuParquetScan.scala:316-458`
host reassembly, `:536-556` device `Table.readParquet`). The TPU-native
split keeps the same shape:

- HOST (control plane, tiny): parse thrift-compact page headers and the
  RLE/bit-packed *run tables* (a few dozen entries per page — runs, not
  values), and locate the dictionary. No value is decoded on the host.
- DEVICE (data plane): ONE jitted program per (shape-bucket) expands
  definition-level runs into the validity mask, expands dictionary-index
  runs (RLE repeats + bit-packed groups extracted straight from the raw
  chunk bytes), and gathers the dictionary — i.e. the decode FLOPs and
  bytes all happen on the accelerator. Upload volume is the raw
  (dictionary-encoded) chunk, typically several times smaller than the
  decoded column.

Scope: flat INT32/INT64 (+DATE/TIMESTAMP, and FLOAT32/FLOAT64 where
the backend has f64) and STRING columns; v1 AND v2 data pages encoded
PLAIN, RLE_DICTIONARY/PLAIN_DICTIONARY, DELTA_BINARY_PACKED (integrals:
the delta recurrence decodes as ONE device cumsum over miniblock-unpacked
deltas, bit widths to 56), DELTA_LENGTH_BYTE_ARRAY (strings: lengths ride
the same delta kernel, byte starts are a device exclusive-sum), or
BYTE_STREAM_SPLIT (fixed-width: strided plane gathers + bitcast), or
DELTA_BYTE_ARRAY (strings: prefix-sharing resolves through a provider
running-max scan, then one gather per output byte; pages whose
values x max-length matrix exceeds the budget fall back). UNCOMPRESSED,
SNAPPY, GZIP, ZSTD and BROTLI codecs.  Compressed pages decompress on the
HOST (block decompression is control-plane: inherently serial bit-stream
work; the reference does it inside cuDF but the data-plane win — run
expansion, dictionary gather, validity spread — is the same either way)
and the decompressed chunk feeds the identical device expansion.  Arrow
remains the oracle and the fallback for everything else (per SURVEY.md
section 7 hard part #2 phasing).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import (
    ColumnarBatch,
    ColumnVector,
    bucket_capacity,
    device_float64_supported,
    physical_np_dtype,
)
from spark_rapids_tpu.columnar.dtypes import DataType


# ---------------------------------------------------------------------------
# Thrift compact-protocol mini reader (PageHeader only)
# ---------------------------------------------------------------------------
class _Compact:
    """Just enough TCompactProtocol to walk parquet PageHeader structs."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = shift = 0
        while True:
            if shift > 63:
                raise ValueError("malformed varint")
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def struct(self) -> dict:
        """Parse a struct into {field_id: value}; nested structs recurse,
        other types reduce to ints / bytes / skipped."""
        out = {}
        fid = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            if b == 0:
                return out
            delta = b >> 4
            ftype = b & 0x0F
            if delta:
                fid += delta
            else:
                fid = self.zigzag()
            out[fid] = self._value(ftype)

    def _value(self, ftype: int):
        if ftype in (1, 2):          # bool true / false
            return ftype == 1
        if ftype == 3:               # i8
            v = self.buf[self.pos]
            self.pos += 1
            return v
        if ftype in (4, 5, 6):       # i16/i32/i64
            return self.zigzag()
        if ftype == 7:               # double
            v = self.buf[self.pos:self.pos + 8]
            self.pos += 8
            return v
        if ftype == 8:               # binary/string
            n = self.varint()
            v = self.buf[self.pos:self.pos + n]
            self.pos += n
            return v
        if ftype == 9:               # list
            b = self.buf[self.pos]
            self.pos += 1
            n = b >> 4
            et = b & 0x0F
            if n == 15:
                n = self.varint()
            if et in (1, 2):         # bools consume no bytes: nothing to walk
                return []
            if n > len(self.buf) - self.pos:
                # each remaining element needs >= 1 byte; a count beyond the
                # buffer is corruption, not a long loop
                raise ValueError("malformed thrift list length")
            return [self._value(et) for _ in range(n)]
        if ftype == 12:              # struct
            return self.struct()
        raise ValueError(f"unsupported thrift compact type {ftype}")


# PageHeader thrift field ids (parquet.thrift)
_PH_TYPE = 1
_PH_UNCOMPRESSED = 2
_PH_COMPRESSED = 3
_PH_DATA_V1 = 5
_PH_DICT = 7
_PH_DATA_V2 = 8
# DataPageHeader fields
_DP_NUM_VALUES = 1
_DP_ENCODING = 2
_DP_DEF_ENC = 3
# DataPageHeaderV2 fields
_D2_NUM_VALUES = 1
_D2_NUM_NULLS = 2
_D2_NUM_ROWS = 3
_D2_ENCODING = 4
_D2_DEF_LEN = 5
_D2_REP_LEN = 6
_D2_IS_COMPRESSED = 7
# DictionaryPageHeader fields
_DI_NUM_VALUES = 1

PAGE_DATA_V1 = 0
PAGE_DICT = 2
PAGE_DATA_V2 = 3
ENC_PLAIN = 0
ENC_PLAIN_DICT = 2
ENC_RLE = 3
ENC_DELTA_BINARY = 5
ENC_DELTA_LENGTH = 6
ENC_DELTA_BYTE_ARRAY = 7
ENC_RLE_DICT = 8
ENC_BYTE_STREAM_SPLIT = 9

# provider-matrix budget for DELTA_BYTE_ARRAY reconstruction (elements);
# pages whose n_values * max_string_len exceed it fall back to Arrow
_DBA_MATRIX_BUDGET = 64 << 20


@dataclass
class PageInfo:
    kind: int            # PAGE_DATA_V1 | PAGE_DICT | PAGE_DATA_V2
    num_values: int
    encoding: int
    data_start: int      # offset of page payload within the chunk bytes
    data_len: int
    uncompressed_len: int = -1  # -1: same as data_len (uncompressed chunk)
    def_len: int = 0     # v2: definition-levels byte length (never prefixed)
    rep_len: int = 0     # v2: repetition-levels byte length (0 for flat)
    data_compressed: bool = True  # v2: is the data section compressed?


def parse_pages(chunk: bytes) -> List[PageInfo]:
    """Walk the page headers of one raw column chunk (native single pass
    when built, thrift-in-Python fallback; the Python walker also speaks
    v2 data pages, which the native one reports as unsupported)."""
    try:
        pages = _parse_pages_native(chunk)
    except _Unsupported:
        return _parse_pages_py(chunk)
    if pages is not NotImplemented:
        return pages
    return _parse_pages_py(chunk)


def _parse_pages_native(chunk: bytes):
    import ctypes

    from spark_rapids_tpu.native import get_lib

    lib = get_lib()
    if lib is None:
        return NotImplemented
    max_pages = 64
    while True:
        kind = np.empty(max_pages, np.int32)
        num_values = np.empty(max_pages, np.int64)
        encoding = np.empty(max_pages, np.int32)
        data_start = np.empty(max_pages, np.int64)
        data_len = np.empty(max_pages, np.int64)
        n = lib.srt_parse_pages(
            chunk, len(chunk),
            kind.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            num_values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            encoding.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            data_start.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            data_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_pages)
        if n == -1:
            max_pages *= 8
            continue
        if n == -4:
            raise _Unsupported("page type not v1/dict")
        if n < 0:
            return NotImplemented  # malformed per native: let python decide
        return [PageInfo(int(kind[i]), int(num_values[i]), int(encoding[i]),
                         int(data_start[i]), int(data_len[i]))
                for i in range(n)]


def _parse_pages_py(chunk: bytes) -> List[PageInfo]:
    pages: List[PageInfo] = []
    pos = 0
    while pos < len(chunk):
        r = _Compact(chunk, pos)
        hdr = r.struct()
        payload = r.pos
        size = hdr[_PH_COMPRESSED]
        usize = hdr.get(_PH_UNCOMPRESSED, size)
        kind = hdr[_PH_TYPE]
        if kind == PAGE_DICT:
            d = hdr[_PH_DICT]
            pages.append(PageInfo(kind, d[_DI_NUM_VALUES], ENC_PLAIN,
                                  payload, size, usize))
        elif kind == PAGE_DATA_V1:
            d = hdr[_PH_DATA_V1]
            pages.append(PageInfo(kind, d[_DP_NUM_VALUES], d[_DP_ENCODING],
                                  payload, size, usize))
        elif kind == PAGE_DATA_V2:
            d = hdr[_PH_DATA_V2]
            pages.append(PageInfo(
                kind, d[_D2_NUM_VALUES], d[_D2_ENCODING], payload, size,
                usize, def_len=d.get(_D2_DEF_LEN, 0),
                rep_len=d.get(_D2_REP_LEN, 0),
                data_compressed=bool(d.get(_D2_IS_COMPRESSED, True))))
        else:  # index pages etc. -> caller falls back to Arrow
            raise _Unsupported(f"page type {kind}")
        pos = payload + size
    return pages


class _Unsupported(Exception):
    pass


# ---------------------------------------------------------------------------
# Host-side page decompression (control plane)
# ---------------------------------------------------------------------------
_CODEC_NAMES = {"SNAPPY": "snappy", "GZIP": "gzip", "ZSTD": "zstd",
                "BROTLI": "brotli"}


@functools.lru_cache(maxsize=None)
def _get_codec(parquet_codec: str):
    """pyarrow block codec for a parquet CompressionCodec name, or None if
    this build of Arrow lacks it. (LZ4/LZO stay unsupported: parquet's LZ4
    framing differs from the lz4-frame codec Arrow exposes.)"""
    name = _CODEC_NAMES.get(parquet_codec)
    if name is None:
        return None
    try:
        import pyarrow as pa

        return pa.Codec(name)
    except Exception:
        return None


def codec_supported(parquet_codec: str) -> bool:
    return parquet_codec == "UNCOMPRESSED" or \
        _get_codec(parquet_codec) is not None


def normalize_chunk(chunk: bytes, codec: str):
    """Decompress every page payload of a raw column chunk, returning
    (uncompressed_chunk_bytes, pages-with-offsets-into-it). v2 pages keep
    their level bytes (stored uncompressed by spec) and decompress only the
    data section. The result feeds the same device expansion kernels as a
    natively UNCOMPRESSED chunk — decompression is host control-plane work,
    the decode data plane stays on the device."""
    pages = _parse_pages_py(chunk)
    if codec == "UNCOMPRESSED":
        return chunk, pages
    dec = _get_codec(codec)
    if dec is None:
        raise _Unsupported(f"codec {codec}")
    out = bytearray()
    new_pages = []
    from dataclasses import replace as _replace

    for p in pages:
        payload = chunk[p.data_start:p.data_start + p.data_len]
        usize = p.uncompressed_len if p.uncompressed_len >= 0 else p.data_len
        if p.kind == PAGE_DATA_V2:
            lvl = p.rep_len + p.def_len
            body = payload[lvl:]
            if p.data_compressed and len(body):
                body = dec.decompress(body, usize - lvl).to_pybytes()
            new_payload = bytes(payload[:lvl]) + bytes(body)
        else:
            new_payload = dec.decompress(payload, usize).to_pybytes() \
                if len(payload) else b""
        start = len(out)
        out += new_payload
        new_pages.append(_replace(p, data_start=start,
                                  data_len=len(new_payload),
                                  uncompressed_len=len(new_payload),
                                  data_compressed=False))
    return bytes(out), new_pages


# ---------------------------------------------------------------------------
# RLE/bit-packed hybrid run tables (host: runs only, never values)
# ---------------------------------------------------------------------------
@dataclass
class RunTable:
    """Decoded structure of one RLE/bit-packed hybrid stream: per run its
    output range and either a repeated value or the absolute BIT offset of
    its packed values within the chunk."""

    out_start: np.ndarray   # int32 [n_runs]
    is_rle: np.ndarray      # bool  [n_runs]
    value: np.ndarray       # int32 [n_runs] (RLE runs)
    bit_off: np.ndarray     # int64 [n_runs] (bit-packed runs, absolute bits)
    total: int              # values described (>= logical count; bp pads to 8)


def parse_runs(chunk: bytes, start: int, end: int, bit_width: int,
               num_values: int) -> RunTable:
    """Run-table extraction; uses the native kernel
    (native/srt_native.cpp srt_parse_runs) when built, else pure Python."""
    native = _parse_runs_native(chunk, start, end, bit_width, num_values)
    if native is not None:
        return native
    return _parse_runs_py(chunk, start, end, bit_width, num_values)


def _parse_runs_native(chunk: bytes, start: int, end: int, bit_width: int,
                       num_values: int) -> Optional[RunTable]:
    import ctypes

    from spark_rapids_tpu.native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    # start small (typical streams have few runs); grow on overflow up to
    # the worst case of one RLE header per value
    max_runs = min(max(64, num_values // 64), num_values + 1)
    while True:
        out_start = np.empty(max_runs, np.int64)
        is_rle = np.empty(max_runs, np.uint8)
        value = np.empty(max_runs, np.int32)
        bit_off = np.empty(max_runs, np.int64)
        produced = ctypes.c_int64(0)
        n = lib.srt_parse_runs(
            chunk, start, end, bit_width, num_values,
            out_start.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            is_rle.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            value.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            bit_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_runs, ctypes.byref(produced))
        if n == -1 and max_runs <= num_values:
            max_runs = min(max_runs * 8, num_values + 1)
            continue
        if n < 0:
            return None
        return RunTable(out_start[:n].astype(np.int32),
                        is_rle[:n].astype(bool),
                        value[:n], bit_off[:n], produced.value)


def _parse_runs_py(chunk: bytes, start: int, end: int, bit_width: int,
                   num_values: int) -> RunTable:
    out_start: List[int] = []
    is_rle: List[bool] = []
    value: List[int] = []
    bit_off: List[int] = []
    r = _Compact(chunk, start)
    produced = 0
    vbytes = (bit_width + 7) // 8
    while produced < num_values and r.pos < end:
        header = r.varint()
        if header & 1:  # bit-packed: (header>>1) groups of 8 values
            groups = header >> 1
            count = groups * 8
            out_start.append(produced)
            is_rle.append(False)
            value.append(0)
            bit_off.append(r.pos * 8)
            r.pos += groups * bit_width
        else:           # RLE run of (header>>1) copies of one LE value
            count = header >> 1
            v = int.from_bytes(chunk[r.pos:r.pos + vbytes], "little")
            r.pos += vbytes
            out_start.append(produced)
            is_rle.append(True)
            value.append(v)
            bit_off.append(0)
        produced += count
    return RunTable(np.asarray(out_start, np.int32),
                    np.asarray(is_rle, bool),
                    np.asarray(value, np.int32),
                    np.asarray(bit_off, np.int64),
                    produced)


# ---------------------------------------------------------------------------
# Device expansion kernels
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnums=(5, 6))
def _expand_hybrid(chunk_u8, out_start, is_rle, value, bit_off,
                   bit_width: int, cap: int):
    """values[j] for j in [0, cap): find j's run (searchsorted), then either
    the run's repeated value or a bit-window extracted from the raw bytes.
    bit_width <= 24 so a 4-byte LE gather always covers the window."""
    j = jnp.arange(cap, dtype=jnp.int32)
    run = jnp.clip(
        jnp.searchsorted(out_start, j, side="right") - 1,
        0, out_start.shape[0] - 1).astype(jnp.int32)
    k = j - out_start[run]
    bitpos = bit_off[run] + k.astype(jnp.int64) * bit_width
    byte = (bitpos >> 3).astype(jnp.int32)
    shift = (bitpos & 7).astype(jnp.int32)
    nbytes = chunk_u8.shape[0]
    b = jnp.zeros((cap,), dtype=jnp.uint32)
    for o in range(4):
        src = jnp.clip(byte + o, 0, nbytes - 1)
        b = b | (chunk_u8[src].astype(jnp.uint32) << (8 * o))
    mask = jnp.uint32((1 << bit_width) - 1) if bit_width < 32 else \
        jnp.uint32(0xFFFFFFFF)
    packed = (b >> shift.astype(jnp.uint32)) & mask
    return jnp.where(is_rle[run], value[run].astype(jnp.uint32),
                     packed).astype(jnp.int32)


def _parse_delta_header(chunk: bytes, pos: int, end: int, n_values: int):
    """Host control plane for one DELTA_BINARY_PACKED page: walk the block/
    miniblock headers into per-miniblock tables (bit offset, width,
    min_delta) — runs-not-values, same discipline as parse_runs. Returns
    (first_value, vpm, mb_bit_off, mb_width, mb_min_delta, data_base)
    where data_base is the first byte past the delta stream (the value
    bytes of a DELTA_LENGTH_BYTE_ARRAY page start there)."""
    r = _Compact(chunk, pos)
    block_size = r.varint()
    mbs_per_block = r.varint()
    total = r.varint()
    first_value = r.zigzag()
    if total != n_values:
        raise _Unsupported(
            f"delta page count {total} != page num_values {n_values}")
    if mbs_per_block <= 0 or block_size % (8 * mbs_per_block) != 0:
        raise _Unsupported("malformed delta block geometry")
    vpm = block_size // mbs_per_block
    ndeltas = total - 1
    mb_off: List[int] = []
    mb_w: List[int] = []
    mb_md: List[int] = []
    idx = 0
    while idx < ndeltas:
        if r.pos >= end:
            raise _Unsupported("truncated delta page")
        min_delta = r.zigzag()
        widths = chunk[r.pos:r.pos + mbs_per_block]
        if len(widths) < mbs_per_block:
            raise _Unsupported("truncated delta miniblock widths")
        r.pos += mbs_per_block
        for w in widths:
            if idx >= ndeltas:
                break  # trailing miniblocks of the last block carry no data
            if w > 56:
                # the 8-byte LE bit-window below covers w + 7 shift bits
                raise _Unsupported(f"delta miniblock bit width {w}")
            mb_off.append(r.pos * 8)
            mb_w.append(int(w))
            mb_md.append(min_delta)
            r.pos += vpm * int(w) // 8
            idx += vpm
        if r.pos > end:
            raise _Unsupported("delta miniblock data past page end")
    if not mb_off:  # 0- or 1-value page: kernel still wants non-empty tables
        mb_off, mb_w, mb_md = [0], [0], [0]
    return (first_value, vpm, np.asarray(mb_off, np.int64),
            np.asarray(mb_w, np.int32), np.asarray(mb_md, np.int64),
            r.pos)  # r.pos = first byte past the delta stream


@functools.partial(jax.jit, static_argnums=(4, 5))
def _expand_delta(chunk_u8, mb_bit_off, mb_width, mb_min_delta,
                  vpm: int, cap: int):
    """DELTA_BINARY_PACKED device expansion: unpack each miniblock-packed
    delta with an 8-byte LE bit window (width <= 56), add its miniblock's
    min_delta, then ONE cumulative sum rebuilds the prefix — the
    delta-decode recurrence is exactly a cumsum, the most TPU-friendly
    shape it could take. Returns the per-index delta PREFIX (value_i -
    first_value); the caller adds first_value."""
    i = jnp.arange(cap, dtype=jnp.int32)
    d = i - 1                    # delta feeding value i (none for i == 0)
    dc = jnp.clip(d, 0, cap - 1)
    m = jnp.clip(dc // vpm, 0, mb_width.shape[0] - 1)
    w = mb_width[m].astype(jnp.int64)
    bitpos = mb_bit_off[m] + (dc % vpm).astype(jnp.int64) * w
    byte = (bitpos >> 3).astype(jnp.int32)
    shift = (bitpos & 7).astype(jnp.uint64)
    nbytes = chunk_u8.shape[0]
    word = jnp.zeros((cap,), dtype=jnp.uint64)
    for o in range(8):
        src = jnp.clip(byte + o, 0, nbytes - 1)
        word = word | (chunk_u8[src].astype(jnp.uint64) << jnp.uint64(8 * o))
    mask = (jnp.uint64(1) << w.astype(jnp.uint64)) - jnp.uint64(1)
    vbits = (word >> shift) & mask
    delta = vbits.astype(jnp.int64) + mb_min_delta[m]
    return jnp.cumsum(jnp.where(d >= 0, delta, 0))


@functools.partial(jax.jit, static_argnums=(4, 5))
def _expand_dba(chunk_u8, plen, slen, suffix_base, maxlen: int,
                byte_cap: int):
    """DELTA_BYTE_ARRAY reconstruction: string i = first plen[i] bytes of
    string i-1 + suffix i. The recurrence vectorizes through a PROVIDER
    matrix: byte j of string i resolves to the suffix byte (j - plen[p])
    of p = max{p' <= i : plen[p'] <= j} — a per-byte-column running max
    (one associative scan over rows), then every output byte is one
    gather. (cuDF's CUDA decoder resolves the same recurrence with a
    block-parallel scan.) plen/slen must be zero beyond the real values.
    Returns (bytes [byte_cap], offsets [n+1])."""
    n = plen.shape[0]
    out_len = plen + slen
    out_off = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(out_len, dtype=jnp.int32)])
    i = jnp.arange(n, dtype=jnp.int32)[:, None]
    j = jnp.arange(maxlen, dtype=jnp.int32)[None, :]
    cand = jnp.where(plen[:, None] <= j, i, -1)
    prov = jax.lax.associative_scan(jnp.maximum, cand, axis=0)
    scum = jnp.cumsum(slen, dtype=jnp.int32)
    sstart = suffix_base.astype(jnp.int32) + scum - slen
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(out_off[1:], pos, side="right"),
                   0, n - 1).astype(jnp.int32)
    jj = pos - out_off[row]
    p = prov[row, jnp.clip(jj, 0, maxlen - 1)]
    pc = jnp.clip(p, 0, n - 1)
    src = sstart[pc] + (jj - plen[pc])
    valid = (pos < out_off[-1]) & (p >= 0)
    byte = chunk_u8[jnp.clip(src, 0, chunk_u8.shape[0] - 1)]
    return jnp.where(valid, byte, 0).astype(jnp.uint8), out_off


@functools.partial(jax.jit, static_argnums=(2, 3))
def _fold_flba_be(chunk_u8, byte_start, count: int, w: int):
    """FIXED_LEN_BYTE_ARRAY decimals: w-byte big-endian two's-complement
    unscaled values folded to int64 (the logical precision <= 18 guarantees
    the value fits, so bytes beyond the low 8 are sign extension)."""
    i = jnp.arange(count, dtype=jnp.int32)
    base = byte_start + i * w
    nbytes = chunk_u8.shape[0]
    word = jnp.zeros((count,), dtype=jnp.uint64)
    for k in range(min(w, 8)):  # k-th byte from the little end
        src = jnp.clip(base + (w - 1 - k), 0, nbytes - 1)
        word = word | (chunk_u8[src].astype(jnp.uint64) << jnp.uint64(8 * k))
    if w < 8:
        sign = (word >> jnp.uint64(8 * w - 1)) & jnp.uint64(1)
        ext = jnp.uint64(((1 << 64) - 1) ^ ((1 << (8 * w)) - 1))
        word = jnp.where(sign == 1, word | ext, word)
    return word.astype(jnp.int64)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _decode_bss(chunk_u8, pos, n, cap: int, np_dtype_name: str):
    """BYTE_STREAM_SPLIT: value i's byte k lives at pos + k*n + i (one
    plane per byte, improving downstream compression). The device
    re-interleaves with w strided gathers + one bitcast."""
    dt = np.dtype(np_dtype_name)
    w = dt.itemsize
    i = jnp.arange(cap, dtype=jnp.int32)
    nbytes = chunk_u8.shape[0]
    planes = [chunk_u8[jnp.clip(pos + k * n + i, 0, nbytes - 1)]
              for k in range(w)]
    return jax.lax.bitcast_convert_type(
        jnp.stack(planes, axis=1), jnp.dtype(dt))


@functools.partial(jax.jit, static_argnums=(2,))
def _extract_bits_lsb(chunk_u8, byte_start, count: int):
    """PLAIN-encoded booleans: one bit per value, LSB-first per byte."""
    i = jnp.arange(count, dtype=jnp.int32)
    nbytes = chunk_u8.shape[0]
    b = chunk_u8[jnp.clip(byte_start + (i >> 3), 0, nbytes - 1)]
    return ((b >> (i & 7).astype(jnp.uint8)) & jnp.uint8(1)).astype(bool)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _bitcast_values(chunk_u8, byte_start, count: int, np_dtype_name: str):
    """PLAIN-encoded fixed-width values: gather + bitcast from raw bytes."""
    dt = np.dtype(np_dtype_name)
    w = dt.itemsize
    idx = byte_start + jnp.arange(count * w, dtype=jnp.int32)
    seg = chunk_u8[jnp.clip(idx, 0, chunk_u8.shape[0] - 1)]
    return jax.lax.bitcast_convert_type(seg.reshape(count, w), jnp.dtype(dt))


@functools.partial(jax.jit, static_argnums=(2,))
def _assemble(validity, dense_vals, cap: int):
    """Spread the dense present-values stream onto its row positions:
    output j takes dense value #(valid-prefix-count of j) when valid."""
    prefix = jnp.cumsum(validity.astype(jnp.int32)) - 1
    slot = jnp.clip(prefix, 0, dense_vals.shape[0] - 1)
    v = dense_vals[slot]
    zero = jnp.zeros((), dtype=v.dtype)
    return jnp.where(validity, v, zero)


# ---------------------------------------------------------------------------
# Column chunk decode driver
# ---------------------------------------------------------------------------
_PHYS_OK = {"INT32": DataType.INT32, "INT64": DataType.INT64,
            "FLOAT": DataType.FLOAT32, "DOUBLE": DataType.FLOAT64,
            "BOOLEAN": DataType.BOOL}


def column_eligible(col_meta, dtype: DataType) -> bool:
    """Can this column chunk decode on device? (codec, physical type,
    encodings; reference analog: GpuParquetScan tagging)."""
    if not codec_supported(col_meta.compression):
        return False
    ok_enc = {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY",
              "DELTA_BINARY_PACKED", "DELTA_LENGTH_BYTE_ARRAY",
              "BYTE_STREAM_SPLIT"}
    if col_meta.physical_type == "BYTE_ARRAY":
        ok_enc = ok_enc | {"DELTA_BYTE_ARRAY"}
    if not set(col_meta.encodings) <= ok_enc:
        return False
    if col_meta.physical_type == "BYTE_ARRAY":
        # strings decode via dictionary gather, plain (start, len) walk,
        # device delta-length expansion, or the DELTA_BYTE_ARRAY
        # provider-scan reconstruction (oversized pages raise _Unsupported
        # at decode and fall back)
        if "DELTA_BINARY_PACKED" in col_meta.encodings or \
                "BYTE_STREAM_SPLIT" in col_meta.encodings:
            return False
        return dtype is DataType.STRING
    if col_meta.physical_type == "FIXED_LEN_BYTE_ARRAY":
        # FLBA decimals: big-endian unscaled fold (decode validates the
        # byte length); any other FLBA use falls back
        from spark_rapids_tpu.columnar.dtypes import is_decimal

        return is_decimal(dtype) and "BYTE_STREAM_SPLIT" not in \
            col_meta.encodings and "DELTA_BINARY_PACKED" not in \
            col_meta.encodings
    if col_meta.physical_type not in _PHYS_OK:
        return False
    from spark_rapids_tpu.columnar.dtypes import is_decimal

    if is_decimal(dtype) and col_meta.physical_type != "INT64":
        # int64-width device paths would misread 4-byte unscaled values;
        # INT64- and FLBA-physical decimals are the in-scope layouts
        return False
    if dtype is DataType.FLOAT64 and not device_float64_supported():
        return False
    return True


def _parse_plain_strings(chunk: bytes, pos: int, end: int, n: int):
    """Host control plane for a PLAIN byte-array data page: per-value
    (absolute start, length) tables — native single pass when built. No
    value bytes are touched; the device gathers them."""
    import ctypes

    from spark_rapids_tpu.native import get_lib

    starts = np.empty(max(n, 1), dtype=np.int32)
    lens = np.empty(max(n, 1), dtype=np.int32)
    lib = get_lib()
    if lib is not None:
        rc = lib.srt_plain_strings(
            chunk, pos, end, n,
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != n:
            raise _Unsupported("truncated PLAIN byte-array page")
        return starts[:n], lens[:n]
    for i in range(n):
        if pos + 4 > end:
            raise _Unsupported("truncated PLAIN byte-array page")
        ln = int.from_bytes(chunk[pos:pos + 4], "little")
        pos += 4
        if ln > end - pos:
            raise _Unsupported("malformed PLAIN byte-array value")
        starts[i] = pos
        lens[i] = ln
        pos += ln
    return starts[:n], lens[:n]


def _parse_dict_strings(chunk: bytes, start: int, n: int):
    """Host control plane for a BYTE_ARRAY dictionary page: entry
    (offset, length) table + one contiguous value-bytes buffer. Value bytes
    copy once; no value is decoded."""
    lens = np.empty(n, dtype=np.int32)
    srcs = np.empty(n, dtype=np.int64)
    pos = start
    limit = len(chunk)
    for i in range(n):
        if pos + 4 > limit:
            raise _Unsupported("truncated dictionary page")
        ln = int.from_bytes(chunk[pos:pos + 4], "little")
        if ln < 0 or pos + 4 + ln > limit:
            raise _Unsupported("malformed dictionary entry")
        srcs[i] = pos + 4
        lens[i] = ln
        pos += 4 + ln
    offs = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lens, out=offs[1:])
    total = int(offs[-1])
    dict_bytes = np.empty(max(total, 1), dtype=np.uint8)
    raw = np.frombuffer(chunk, dtype=np.uint8)
    for i in range(n):
        dict_bytes[offs[i]:offs[i + 1]] = raw[srcs[i]:srcs[i] + lens[i]]
    return dict_bytes, offs, lens


def _host_count_ones(chunk_np: np.ndarray, rt: RunTable, n: int) -> int:
    """Number of 1-bits among the first n values of a bit-width-1 hybrid
    stream, computed ON HOST from the run table + raw bytes. This is what
    lets the whole-chunk flat decode know every page's present-value count
    without the per-page device round trip that cost the device tier 12x
    vs host decode (BENCH_DECODE_r04.json: one ~66 ms sync per page)."""
    total = 0
    n_runs = len(rt.out_start)
    for i in range(n_runs):
        start = int(rt.out_start[i])
        end = int(rt.out_start[i + 1]) if i + 1 < n_runs else rt.total
        cnt = min(end, n) - start
        if cnt <= 0:
            continue
        if rt.is_rle[i]:
            total += (int(rt.value[i]) & 1) * cnt
        else:
            b0 = int(rt.bit_off[i]) >> 3  # byte-aligned for bit-packed runs
            nb = (cnt + 7) >> 3
            bits = np.unpackbits(chunk_np[b0:b0 + nb], bitorder="little")
            total += int(bits[:cnt].sum())
    return total


def _shifted_tab(rt: RunTable, row_shift: int, n: int):
    """Run table adjusted to a chunk-global output offset (numpy)."""
    return (rt.out_start.astype(np.int32) + np.int32(row_shift),
            rt.is_rle.astype(bool), rt.value.astype(np.int32),
            rt.bit_off.astype(np.int64))


def _synth_rle_tab(row_shift: int, value: int):
    return (np.asarray([row_shift], np.int32), np.asarray([True], bool),
            np.asarray([value], np.int32), np.asarray([0], np.int64))


def _pack_flat_tabs(tabs):
    """Concatenate shifted run tables and pad the run count to a pow2
    bucket (pads carry out_start = INT32_MAX so searchsorted never selects
    them) — run-count variation between chunks must not retrace."""
    out_start = np.concatenate([t[0] for t in tabs])
    is_rle = np.concatenate([t[1] for t in tabs])
    value = np.concatenate([t[2] for t in tabs])
    bit_off = np.concatenate([t[3] for t in tabs])
    n = len(out_start)
    padded = max(8, 1 << (n - 1).bit_length()) if n else 8
    if padded > n:
        pad = padded - n
        out_start = np.pad(out_start, (0, pad),
                           constant_values=np.iinfo(np.int32).max)
        is_rle = np.pad(is_rle, (0, pad), constant_values=True)
        value = np.pad(value, (0, pad))
        bit_off = np.pad(bit_off, (0, pad))
    return (out_start, is_rle, value, bit_off)


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7))
def _flat_dict_kernel(chunk_u8, def_tab, val_tab, dict_vals, bw: int,
                      cap: int, cap_p: int, has_def: bool):
    """Whole-chunk dictionary decode in one program: validity expansion,
    index expansion, dictionary gather, dense->row assembly."""
    if has_def:
        validity = _expand_hybrid(chunk_u8, *def_tab, 1, cap).astype(bool)
    else:
        validity = jnp.ones((cap,), bool)
    idx = _expand_hybrid(chunk_u8, *val_tab, bw, cap_p)
    dense = dict_vals[jnp.clip(idx, 0, dict_vals.shape[0] - 1)]
    return dense, validity


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _flat_dict_codes_kernel(chunk_u8, def_tab, val_tab, bw: int,
                            cap: int, cap_p: int, has_def: bool):
    """_flat_dict_kernel WITHOUT the dictionary gather: the expanded
    index stream IS the encoded column's code array
    (columnar/encoded.py — fixed-value dictionary chunks)."""
    if has_def:
        validity = _expand_hybrid(chunk_u8, *def_tab, 1, cap).astype(bool)
    else:
        validity = jnp.ones((cap,), bool)
    idx = _expand_hybrid(chunk_u8, *val_tab, bw, cap_p)
    return idx.astype(jnp.int32), validity


def _rle_run_table(val_tabs, num_rows: int):
    """Host RunTable (columnar/runs.py) from a chunk's PURE-RLE value run
    tables, or None when any bit-packed group is present (its values are
    not host-known) or the stream is empty. Only meaningful for all-
    present chunks (no def levels): run output offsets are then row
    offsets."""
    from spark_rapids_tpu.columnar.runs import RunTable as _RT

    starts_parts = []
    values_parts = []
    for out_start, is_rle, value, _bit_off in val_tabs:
        if not bool(np.all(is_rle)):
            return None
        starts_parts.append(out_start.astype(np.int64))
        values_parts.append(value)
    if not starts_parts:
        return None
    starts = np.concatenate(starts_parts)
    values = np.concatenate(values_parts)
    keep = starts < num_rows
    starts, values = starts[keep], values[keep]
    if len(starts) == 0 or starts[0] != 0 or \
            bool(np.any(np.diff(starts) <= 0)):
        return None
    return _RT(starts, values, num_rows)


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _flat_plain_kernel(chunk_u8, def_tab, page_meta, np_dtype_name: str,
                       cap: int, cap_p: int, has_def: bool):
    """Whole-chunk PLAIN decode: per-lane page lookup (searchsorted over
    dense offsets), byte gather, bitcast. page_meta: int32/int64 [2, m] =
    (dense_end, byte_pos)."""
    if has_def:
        validity = _expand_hybrid(chunk_u8, *def_tab, 1, cap).astype(bool)
    else:
        validity = jnp.ones((cap,), bool)
    dt = np.dtype(np_dtype_name)
    w = dt.itemsize
    i = jnp.arange(cap_p, dtype=jnp.int32)
    dense_end = page_meta[0]
    page = jnp.searchsorted(dense_end, i, side="right").astype(jnp.int32)
    page = jnp.minimum(page, dense_end.shape[0] - 1)
    dense_start = jnp.concatenate([jnp.zeros((1,), dense_end.dtype),
                                   dense_end[:-1]])
    local = i - dense_start[page]
    base = page_meta[1][page] + local.astype(page_meta.dtype) * w
    idx = base[:, None] + jnp.arange(w, dtype=page_meta.dtype)[None, :]
    seg = chunk_u8[jnp.clip(idx, 0, chunk_u8.shape[0] - 1)]
    dense = jax.lax.bitcast_convert_type(seg.reshape(cap_p, w),
                                         jnp.dtype(dt))
    return dense, validity


@functools.partial(jax.jit, static_argnums=(3,))
def _flat_finish(dense, validity, nums, cap: int):
    """Mask validity to the row count and spread dense values to rows."""
    validity = validity & (jnp.arange(cap) < nums[0])
    data = _assemble(validity, dense, cap)
    return data, validity


_FIXED_ENC_DTYPES = (DataType.INT64, DataType.DATE, DataType.TIMESTAMP)


def _try_flat_fixed(chunk: bytes, chunk_dev, pages, dtype: DataType,
                    num_rows: int, max_def: int, cap: int, npdt,
                    encoded_ok: bool = False,
                    max_dict_fraction: float = 1.0):
    """Whole-chunk fixed-width decode with ZERO per-page device work:
    host computes every page's present count (bit-popcount over def-level
    bytes), all pages' run tables concatenate into one flat table (output
    offsets made chunk-global; bit offsets are already chunk-absolute),
    and 2-3 jitted dispatches decode the entire chunk. Returns a
    ColumnVector, or None when the chunk's shape needs the general
    per-page path (mixed/exotic encodings, strings, bools, FLBA).

    With `encoded_ok`, an INT64/DATE/TIMESTAMP dictionary chunk clearing
    the ndv/rows heuristic emits a DictionaryColumn instead: codes ARE
    the expanded index stream (no dictionary gather) and the host-parsed
    PLAIN dictionary page interns into one shared fixed-value
    DeviceDictionary (ROADMAP item 5: INT64 dictionary chunks). Either
    way, an all-present pure-RLE value stream additionally attaches a
    host RunTable for the run-granular aggregate path
    (columnar/runs.py).

    Reference bar: on-accelerator decode is the FAST path
    (GpuParquetScan.scala:536-556); round 4's per-page loop paid one
    ~66 ms sync + ~9 eager dispatches per page through the tunnel
    (tools/decode_census.py: 648 syncs + 6015 eager ops per iteration)."""
    from spark_rapids_tpu.columnar.batch import ColumnVector
    from spark_rapids_tpu.columnar.dtypes import is_decimal

    if dtype in (DataType.STRING, DataType.BOOL):
        return None
    if is_decimal(dtype) and np.dtype(npdt) not in (np.dtype(np.int32),
                                                    np.dtype(np.int64)):
        return None
    data_pages = [p for p in pages if p.kind in (PAGE_DATA_V1,
                                                 PAGE_DATA_V2)]
    dict_pages = [p for p in pages if p.kind == PAGE_DICT]
    if not data_pages or len(dict_pages) > 1:
        return None
    if any(p.rep_len for p in data_pages):
        return None
    encs = {p.encoding for p in data_pages}
    dict_mode = bool(dict_pages) and encs <= {ENC_PLAIN_DICT, ENC_RLE_DICT}
    plain_mode = not dict_pages and encs == {ENC_PLAIN}
    if not (dict_mode or plain_mode):
        return None
    chunk_np = np.frombuffer(chunk, dtype=np.uint8)
    def_tabs = []
    val_tabs = []
    plain_dense_end = []
    plain_pos = []
    rows = 0
    present = 0
    bw = None
    for p in data_pages:
        pos = p.data_start
        end = p.data_start + p.data_len
        if p.kind == PAGE_DATA_V2:
            if max_def > 0 and p.def_len > 0:
                rt = parse_runs(chunk, pos, pos + p.def_len, 1,
                                p.num_values)
                n_present = _host_count_ones(chunk_np, rt, p.num_values)
                def_tabs.append(_shifted_tab(rt, rows, p.num_values))
            else:
                n_present = p.num_values
                def_tabs.append(_synth_rle_tab(rows, 1))
            pos += p.def_len
        elif max_def > 0:
            dl_len = int.from_bytes(chunk[pos:pos + 4], "little")
            rt = parse_runs(chunk, pos + 4, pos + 4 + dl_len, 1,
                            p.num_values)
            n_present = _host_count_ones(chunk_np, rt, p.num_values)
            def_tabs.append(_shifted_tab(rt, rows, p.num_values))
            pos += 4 + dl_len
        else:
            n_present = p.num_values
            def_tabs.append(_synth_rle_tab(rows, 1))
        if dict_mode:
            pbw = chunk[pos]
            pos += 1
            if pbw > 24:
                return None
            if pbw == 0:
                val_tabs.append(_synth_rle_tab(present, 0))
            else:
                if bw is None:
                    bw = pbw
                elif bw != pbw:
                    return None
                rt = parse_runs(chunk, pos, end, pbw, n_present)
                val_tabs.append(_shifted_tab(rt, present, n_present))
        else:
            plain_dense_end.append(present + n_present)
            plain_pos.append(pos)
        rows += p.num_values
        present += n_present
    has_def = max_def > 0
    cap_p = bucket_capacity(max(present, 1))
    def_tab = tuple(jnp.asarray(a) for a in _pack_flat_tabs(def_tabs)) \
        if has_def else _EMPTY_TAB()
    nums = np.asarray([num_rows, present], np.int32)
    if dict_mode:
        dp = dict_pages[0]
        # host run table: only when the whole chunk is present (run
        # output offsets == row offsets — a nullable schema still
        # qualifies as long as no NULL actually occurs) and every value
        # run is RLE
        runs = _rle_run_table(val_tabs, num_rows) if present == rows \
            else None
        if encoded_ok and dtype in _FIXED_ENC_DTYPES:
            from spark_rapids_tpu.columnar.encoded import (
                DeviceDictionary,
                DictionaryColumn,
                scan_encoded_ok,
            )

            if scan_encoded_ok(dp.num_values, num_rows,
                               max_dict_fraction):
                host_vals = np.frombuffer(
                    chunk, dtype=np.dtype(npdt), count=dp.num_values,
                    offset=dp.data_start).astype(dtype.to_np())
                d = DeviceDictionary.from_fixed_values(host_vals, dtype)
                val_tab = tuple(jnp.asarray(a)
                                for a in _pack_flat_tabs(val_tabs))
                codes, validity = _flat_dict_codes_kernel(
                    chunk_dev, def_tab, val_tab, int(bw or 1), cap,
                    cap_p, has_def)
                codes, validity = _flat_finish(codes, validity, nums, cap)
                out = DictionaryColumn(dtype, codes, validity, d)
                out.runs = runs  # run values ARE codes for encoded cols
                return out
        dict_vals = _bitcast_values(chunk_dev, np.int32(dp.data_start),
                                    dp.num_values, np.dtype(npdt).name)
        val_tab = tuple(jnp.asarray(a) for a in _pack_flat_tabs(val_tabs))
        dense, validity = _flat_dict_kernel(
            chunk_dev, def_tab, val_tab, dict_vals, int(bw or 1), cap,
            cap_p, has_def)
        runs_out = None
        if runs is not None and dp.num_values:
            # decoded emission still benefits from runs: values via one
            # host take through the dictionary page's raw values
            from spark_rapids_tpu.columnar.runs import RunTable as _RT

            host_vals = np.frombuffer(
                chunk, dtype=np.dtype(npdt), count=dp.num_values,
                offset=dp.data_start)
            sel = np.clip(runs.values, 0, dp.num_values - 1)
            runs_out = _RT(runs.starts,
                           host_vals[sel].astype(dtype.to_np()), num_rows)
        data, validity = _flat_finish(dense, validity, nums, cap)
        out = ColumnVector(dtype, data, validity)
        out.runs = runs_out
        return out
    else:
        meta = np.zeros((2, len(plain_pos)), np.int64)
        meta[0] = plain_dense_end
        meta[1] = plain_pos
        if int(meta.max()) * np.dtype(npdt).itemsize < (1 << 31):
            meta = meta.astype(np.int32)
        dense, validity = _flat_plain_kernel(
            chunk_dev, def_tab, meta, np.dtype(npdt).name, cap, cap_p,
            has_def)
    data, validity = _flat_finish(dense, validity, nums, cap)
    return ColumnVector(dtype, data, validity)


_EMPTY_TAB_CACHE = None


def _EMPTY_TAB():
    # cached: rebuilding would pay 4 host->device uploads per chunk of
    # every required column (device_const-style interning, local form)
    global _EMPTY_TAB_CACHE
    if _EMPTY_TAB_CACHE is None:
        _EMPTY_TAB_CACHE = (
            jnp.asarray(np.full((1,), np.iinfo(np.int32).max, np.int32)),
            jnp.asarray(np.ones((1,), bool)),
            jnp.asarray(np.zeros((1,), np.int32)),
            jnp.asarray(np.zeros((1,), np.int64)))
    return _EMPTY_TAB_CACHE


def decode_chunk_device(chunk: bytes, dtype: DataType, num_rows: int,
                        max_def: int, cap: Optional[int] = None,
                        codec: str = "UNCOMPRESSED", flba_len: int = 0,
                        encoded_ok: bool = False,
                        max_dict_fraction: float = 1.0):
    """Decode one raw column chunk into a device ColumnVector.

    Fixed-width columns: PLAIN / dictionary pages, v1 or v2. STRING
    columns: dictionary pages (host parses the (offset, length) dictionary
    table, values gather through it) or PLAIN byte-array pages (host walks
    per-value (start, len) tables — native single pass — and the device
    gathers the bytes); a chunk mixing both falls back. Either way the
    output column is one jitted gather through build_from_plan (reference
    decodes strings on the accelerator via cudf the same way,
    GpuParquetScan.scala:536-556).
    Compressed chunks (snappy/gzip/zstd/brotli) decompress page-by-page on
    the host first (normalize_chunk); the device data plane is identical.

    max_def: 1 for nullable columns (def levels present), 0 for required.
    Raises _Unsupported for shapes outside scope (caller falls back to the
    Arrow host path)."""
    from spark_rapids_tpu.columnar.batch import ColumnVector

    if codec != "UNCOMPRESSED":
        chunk, pages = normalize_chunk(chunk, codec)
    else:
        pages = parse_pages(chunk)
    from spark_rapids_tpu.columnar.dtypes import is_decimal

    cap = cap or bucket_capacity(max(num_rows, 1))
    is_string = dtype is DataType.STRING
    # flba_len == 0 with a decimal dtype means the column is physical
    # INT64 (column_eligible rejects other widths): the generic
    # fixed-width paths below read it correctly since npdt is int64
    is_dec_flba = is_decimal(dtype) and flba_len > 0
    if is_dec_flba and not 1 <= flba_len <= 16:
        raise _Unsupported(f"FLBA decimal byte length {flba_len}")
    npdt = np.dtype(np.int32) if is_string else physical_np_dtype(dtype)
    chunk_dev = jnp.asarray(np.frombuffer(chunk, dtype=np.uint8))

    if not is_string and not is_dec_flba:
        flat = _try_flat_fixed(chunk, chunk_dev, pages, dtype, num_rows,
                               max_def, cap, npdt,
                               encoded_ok=encoded_ok,
                               max_dict_fraction=max_dict_fraction)
        if flat is not None:
            return flat

    dict_vals = None          # fixed-width dictionary values (device)
    str_dict = None           # (bytes_dev, offs_dev, lens_dev) for strings
    str_dict_host = None      # host (bytes_np, offs_np) dictionary table
    str_run_tabs = []         # per-page value run tables (no-null chunks)
    row_base = 0              # rows decoded so far (run-table shifting)
    str_plain = []            # per-page (starts_np, lens_np) for strings
    str_delta = []            # per-page DEVICE (starts, lens, n) for
                              # DELTA_LENGTH_BYTE_ARRAY strings
    str_delta_bytes = 0       # host-known total value bytes across pages
    str_dba = []              # per-page (bytes_dev, starts, lens, n, total)
    dense_parts = []
    valid_parts = []
    for p in pages:
        if p.kind == PAGE_DICT:
            if is_string:
                db, do, dl = _parse_dict_strings(chunk, p.data_start,
                                                 p.num_values)
                str_dict_host = (db, do)
                str_dict = (jnp.asarray(db), jnp.asarray(do),
                            jnp.asarray(dl))
            elif is_dec_flba:
                dict_vals = _fold_flba_be(chunk_dev,
                                          jnp.int32(p.data_start),
                                          p.num_values, flba_len)
            else:
                dict_vals = _bitcast_values(
                    chunk_dev, jnp.int32(p.data_start), p.num_values,
                    npdt.name)
            continue
        is_bool = dtype is DataType.BOOL
        ok_encs = (ENC_PLAIN, ENC_PLAIN_DICT, ENC_RLE_DICT) + \
            ((ENC_RLE,) if is_bool else ()) + \
            (() if (is_bool or is_string)
             else (ENC_DELTA_BINARY, ENC_BYTE_STREAM_SPLIT)) + \
            ((ENC_DELTA_LENGTH, ENC_DELTA_BYTE_ARRAY)
             if is_string else ())
        if p.encoding not in ok_encs:
            raise _Unsupported(f"data page encoding {p.encoding}")
        pos = p.data_start
        end = p.data_start + p.data_len
        page_cap = bucket_capacity(max(p.num_values, 1))
        if p.kind == PAGE_DATA_V2:
            # v2: rep/def level bytes sit unprefixed (and uncompressed)
            # ahead of the data section, lengths from the page header
            if p.rep_len:
                raise _Unsupported("repetition levels (nested) in v2 page")
            if max_def > 0 and p.def_len > 0:
                rt = parse_runs(chunk, pos, pos + p.def_len, 1,
                                p.num_values)
                page_valid = _expand_hybrid(
                    chunk_dev, jnp.asarray(rt.out_start),
                    jnp.asarray(rt.is_rle), jnp.asarray(rt.value),
                    jnp.asarray(rt.bit_off), 1, page_cap).astype(bool)
            else:
                page_valid = jnp.ones((page_cap,), dtype=bool)
            pos += p.def_len
        elif max_def > 0:
            # v1 def levels: u32 length prefix + RLE hybrid, bit width 1
            dl_len = int.from_bytes(chunk[pos:pos + 4], "little")
            rt = parse_runs(chunk, pos + 4, pos + 4 + dl_len, 1,
                            p.num_values)
            page_valid = _expand_hybrid(
                chunk_dev, jnp.asarray(rt.out_start), jnp.asarray(rt.is_rle),
                jnp.asarray(rt.value), jnp.asarray(rt.bit_off), 1,
                page_cap).astype(bool)
            pos += 4 + dl_len
        else:
            page_valid = jnp.ones((page_cap,), dtype=bool)
        page_valid = page_valid & (jnp.arange(page_cap) < p.num_values)
        n_present = int(jax.device_get(jnp.sum(page_valid)))
        if p.encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
            if dict_vals is None and str_dict is None:
                raise _Unsupported("dictionary-encoded page before dict")
            bit_width = chunk[pos]
            if bit_width > 24:
                raise _Unsupported(f"dict index bit width {bit_width}")
            pos += 1
            all_present = n_present == p.num_values
            if bit_width == 0:
                idx = jnp.zeros((page_cap,), dtype=jnp.int32)
                if all_present:
                    str_run_tabs.append(_synth_rle_tab(row_base, 0))
            else:
                rt = parse_runs(chunk, pos, end, bit_width, n_present)
                idx = _expand_hybrid(
                    chunk_dev, jnp.asarray(rt.out_start),
                    jnp.asarray(rt.is_rle), jnp.asarray(rt.value),
                    jnp.asarray(rt.bit_off), bit_width, page_cap)
                if all_present:
                    str_run_tabs.append(
                        _shifted_tab(rt, row_base, n_present))
            if is_string:
                page_dense = idx  # gather through the dict AFTER assembly
            else:
                page_dense = dict_vals[jnp.clip(idx, 0,
                                                dict_vals.shape[0] - 1)]
        elif is_bool and p.encoding == ENC_RLE:
            # v2 boolean values: length-prefixed RLE hybrid, bit width 1
            rl_len = int.from_bytes(chunk[pos:pos + 4], "little")
            if pos + 4 + rl_len > end:
                # corrupt/truncated length prefix: decoding would walk into
                # the next page's bytes — fall back rather than misread
                raise _Unsupported(
                    f"boolean RLE length {rl_len} exceeds page data section")
            brt = parse_runs(chunk, pos + 4, pos + 4 + rl_len, 1,
                             n_present)
            page_dense = _expand_hybrid(
                chunk_dev, jnp.asarray(brt.out_start),
                jnp.asarray(brt.is_rle), jnp.asarray(brt.value),
                jnp.asarray(brt.bit_off), 1, page_cap).astype(bool)
        elif is_bool:  # PLAIN booleans: LSB-first bit-packed
            page_dense = _extract_bits_lsb(chunk_dev, jnp.int32(pos),
                                           page_cap)
        elif p.encoding == ENC_DELTA_BINARY:
            if not np.issubdtype(npdt, np.integer):
                raise _Unsupported("DELTA_BINARY_PACKED on non-integral")
            first_value, vpm, mb_off, mb_w, mb_md, _base = \
                _parse_delta_header(chunk, pos, end, n_present)
            prefix = _expand_delta(chunk_dev, jnp.asarray(mb_off),
                                   jnp.asarray(mb_w), jnp.asarray(mb_md),
                                   vpm, page_cap)
            # int64 arithmetic wraps mod 2^64; the final astype wraps a
            # 32-bit column the way the encoding's modular deltas require
            page_dense = (jnp.int64(first_value) + prefix).astype(npdt)
        elif p.encoding == ENC_DELTA_LENGTH and is_string:
            # DELTA_LENGTH_BYTE_ARRAY: delta-packed lengths, then the
            # value bytes concatenated — lengths expand through the SAME
            # delta cumsum kernel and exclusive-summed into byte starts,
            # all on device; total byte size is host-known from the page
            # layout (no sync)
            first_value, vpm, mb_off, mb_w, mb_md, data_base = \
                _parse_delta_header(chunk, pos, end, n_present)
            prefix = _expand_delta(chunk_dev, jnp.asarray(mb_off),
                                   jnp.asarray(mb_w), jnp.asarray(mb_md),
                                   vpm, page_cap)
            in_page = jnp.arange(page_cap) < n_present
            lens_dev = jnp.where(in_page, jnp.int64(first_value) + prefix, 0)
            cl = jnp.cumsum(lens_dev)
            starts_dev = jnp.int64(data_base) + cl - lens_dev
            str_delta.append((starts_dev.astype(jnp.int32),
                              lens_dev.astype(jnp.int32), n_present))
            str_delta_bytes += max(0, end - data_base)
            page_dense = None
        elif p.encoding == ENC_DELTA_BYTE_ARRAY and is_string:
            # two delta streams (prefix lengths, suffix lengths) then the
            # concatenated suffix bytes
            fv1, vpm1, o1, w1, m1, base1 = \
                _parse_delta_header(chunk, pos, end, n_present)
            pp = _expand_delta(chunk_dev, jnp.asarray(o1), jnp.asarray(w1),
                               jnp.asarray(m1), vpm1, page_cap)
            in_page = jnp.arange(page_cap) < n_present
            plen_dev = jnp.where(in_page, jnp.int64(fv1) + pp,
                                 0).astype(jnp.int32)
            fv2, vpm2, o2, w2, m2, base2 = \
                _parse_delta_header(chunk, base1, end, n_present)
            sp = _expand_delta(chunk_dev, jnp.asarray(o2), jnp.asarray(w2),
                               jnp.asarray(m2), vpm2, page_cap)
            slen_dev = jnp.where(in_page, jnp.int64(fv2) + sp,
                                 0).astype(jnp.int32)
            # one host sync sizes the provider matrix + byte buffer
            maxlen, total = (int(x) for x in jax.device_get(
                (jnp.max(plen_dev + slen_dev), jnp.sum(plen_dev + slen_dev))))
            mlen_cap = bucket_capacity(max(maxlen, 1))
            if page_cap * mlen_cap > _DBA_MATRIX_BUDGET:
                raise _Unsupported(
                    "DELTA_BYTE_ARRAY provider matrix over budget")
            rec, out_off = _expand_dba(chunk_dev, plen_dev, slen_dev,
                                       jnp.int32(base2), mlen_cap,
                                       bucket_capacity(max(total, 8)))
            str_dba.append((rec, out_off[:-1], plen_dev + slen_dev,
                            n_present, total))
            page_dense = None
        elif p.encoding == ENC_BYTE_STREAM_SPLIT:
            # npdt.itemsize == the file's physical width here: eligibility
            # rejects FLOAT64 columns unless the device stores real f64
            # (same assumption the PLAIN bitcast path makes)
            page_dense = _decode_bss(chunk_dev, jnp.int32(pos),
                                     jnp.int32(n_present), page_cap,
                                     npdt.name)
        elif is_string:  # PLAIN byte-array: host (start, len) walk
            ps, pl = _parse_plain_strings(chunk, pos, end, n_present)
            str_plain.append((ps, pl))
            page_dense = None  # plain-string chunks skip dense assembly
        elif is_dec_flba:  # PLAIN FLBA decimal: big-endian fold
            page_dense = _fold_flba_be(chunk_dev, jnp.int32(pos),
                                       page_cap, flba_len)
        else:  # PLAIN fixed-width
            page_dense = _bitcast_values(chunk_dev, jnp.int32(pos),
                                         page_cap, npdt.name)
            # only the first n_present values are real; tail reads past the
            # page but is masked by validity at assemble time
        if page_dense is not None:
            dense_parts.append((page_dense, n_present))
        valid_parts.append((page_valid, p.num_values))
        row_base += p.num_values

    # stitch pages (single-page chunks — the common case with row-group
    # splits — take the fast path)
    if len(valid_parts) == 1:
        validity = _pad_to(valid_parts[0][0], cap, False)
    else:
        validity = _concat_logical(
            [(v, n) for v, n in valid_parts], cap, False)
    if not str_plain and not str_delta and not str_dba:
        # plain/delta-length string chunks skip the dense assembly — their
        # values come from the (start, len) tables below
        if len(dense_parts) == 1:
            dense = _pad_to(dense_parts[0][0], cap, 0)
        else:
            dense = _concat_logical(
                [(d, n) for d, n in dense_parts], cap, 0)
        data = _assemble(validity, dense, cap)
    if not is_string:
        return ColumnVector(dtype, data, validity)
    from spark_rapids_tpu.columnar.strings import build_from_plan

    if str_dba:
        if str_dict is not None or str_plain or str_delta:
            raise _Unsupported("mixed DELTA_BYTE_ARRAY/other string pages")
        # values live in per-page reconstructed buffers; build_from_plan's
        # multi-source gather stitches them (source = page index)
        starts_dev = _concat_logical(
            [(s, n) for _b, s, _l, n, _t in str_dba], cap, 0)
        lens_dev = _concat_logical(
            [(l, n) for _b, _s, l, n, _t in str_dba], cap, 0)
        page_ids = _concat_logical(
            [(jnp.full((n,), pi, jnp.int32), n)
             for pi, (_b, _s, _l, n, _t) in enumerate(str_dba)], cap, 0)
        row_starts = _assemble(validity, starts_dev, cap)
        row_lens = _assemble(validity, lens_dev, cap)
        row_choice = _assemble(validity, page_ids, cap)
        byte_cap = bucket_capacity(
            max(sum(t for *_x, t in str_dba), 8))
        out_bytes, offsets = build_from_plan(
            [b for b, *_x in str_dba], row_choice, row_starts,
            jnp.where(validity, row_lens, 0), byte_cap)
        return ColumnVector(dtype, out_bytes, validity, offsets)
    if str_delta:
        if str_dict is not None or str_plain:
            raise _Unsupported("mixed delta-length/other string pages")
        # per-page DEVICE (start, len) tables from the delta expansion;
        # total byte size came from the page layout — no sync
        starts_dev = _concat_logical([(s, n) for s, _l, n in str_delta],
                                     cap, 0)
        lens_dev = _concat_logical([(l, n) for _s, l, n in str_delta],
                                   cap, 0)
        row_starts = _assemble(validity, starts_dev, cap)
        row_lens = _assemble(validity, lens_dev, cap)
        byte_cap = bucket_capacity(max(str_delta_bytes, 8))
        out_bytes, offsets = build_from_plan(
            [chunk_dev], jnp.zeros((cap,), jnp.int32),
            row_starts, jnp.where(validity, row_lens, 0), byte_cap)
        return ColumnVector(dtype, out_bytes, validity, offsets)
    if str_plain and str_dict is None:
        # PLAIN byte-array pages: per-present (start, len) from the host
        # walk; the device gathers the value bytes in one pass. Total byte
        # size is host-known — no device sync.
        starts_np = np.concatenate([s for s, _l in str_plain])
        lens_np = np.concatenate([l for _s, l in str_plain])
        total = int(lens_np.sum())
        pad = max(0, cap - starts_np.shape[0])
        dstarts = jnp.asarray(np.pad(starts_np, (0, pad))[:cap])
        dlens = jnp.asarray(np.pad(lens_np, (0, pad))[:cap])
        row_starts = _assemble(validity, dstarts, cap)
        row_lens = _assemble(validity, dlens, cap)
        byte_cap = bucket_capacity(max(total, 8))
        out_bytes, offsets = build_from_plan(
            [chunk_dev], jnp.zeros((cap,), jnp.int32),
            row_starts, row_lens, byte_cap)
        return ColumnVector(dtype, out_bytes, validity, offsets)
    if str_dict is None:
        raise _Unsupported("string chunk without a dictionary page")
    if str_plain:
        raise _Unsupported("mixed dictionary/plain string pages")
    dict_bytes, dict_offs, dict_lens = str_dict
    if encoded_ok and str_dict_host is not None:
        # keep the column ENCODED: the codes ARE the decoded index stream
        # (`data`), and the host-parsed dictionary table interns into one
        # shared DeviceDictionary — no dictionary gather, no byte-total
        # sync, and several-x less HBM (columnar/encoded.py; conf
        # rapids.tpu.sql.encoded.*)
        from spark_rapids_tpu.columnar.encoded import (
            DeviceDictionary,
            DictionaryColumn,
            scan_encoded_ok,
        )

        db, do = str_dict_host
        if scan_encoded_ok(int(len(do)) - 1, num_rows, max_dict_fraction):
            d = DeviceDictionary.from_byte_table(db, do)
            out = DictionaryColumn(dtype, data.astype(jnp.int32),
                                   validity, d)
            if len(str_run_tabs) == len(
                    [p for p in pages if p.kind != PAGE_DICT]):
                # all-present pure-RLE index stream: attach the host run
                # table for run-granular compute (values are CODES)
                out.runs = _rle_run_table(str_run_tabs, num_rows)
            return out
    row_idx = jnp.clip(data, 0, dict_lens.shape[0] - 1)
    row_lens = jnp.where(validity, dict_lens[row_idx], 0)
    total = int(jax.device_get(jnp.sum(row_lens)))
    byte_cap = bucket_capacity(max(total, 8))
    out_bytes, offsets = build_from_plan(
        [dict_bytes], jnp.zeros((cap,), jnp.int32),
        dict_offs[row_idx], row_lens, byte_cap)
    return ColumnVector(dtype, out_bytes, validity, offsets)


def _pad_to(arr, cap: int, fill):
    if arr.shape[0] == cap:
        return arr
    if arr.shape[0] > cap:
        return arr[:cap]
    pad = jnp.full((cap - arr.shape[0],), fill, dtype=arr.dtype)
    return jnp.concatenate([arr, pad])


def _concat_logical(parts, cap: int, fill):
    """Concatenate the first n logical elements of each part."""
    segs = [p[:n] for p, n in parts]
    out = jnp.concatenate(segs)
    return _pad_to(out, cap, fill)


def chunk_dict_ndv(path: str, col_meta) -> Optional[int]:
    """num_values of a chunk's dictionary page from a header-only read
    (a few hundred bytes at the dictionary page offset), or None when
    the chunk has no dictionary page / the header is unreadable. The
    plan-time half of the encoded-scan heuristic: the resource analyzer
    must apply the SAME ndv/rows test the runtime decode applies, or its
    encoded-column byte model would diverge from what executes."""
    start = getattr(col_meta, "dictionary_page_offset", None)
    if start is None or start <= 0:
        return None
    try:
        with open(path, "rb") as f:
            f.seek(start)
            head = f.read(512)
        r = _Compact(head, 0)
        hdr = r.struct()
        if hdr.get(_PH_TYPE) != PAGE_DICT:
            return None
        return int(hdr[_PH_DICT][_DI_NUM_VALUES])
    except Exception:
        return None


def chunk_dict_only(path: str, col_meta) -> Optional[bool]:
    """True when EVERY data page of the chunk is dictionary-encoded,
    proven by walking the page HEADERS only (one small read per page;
    payloads are skipped by their header-declared size). False when a
    PLAIN fallback page exists — the footer's `encodings` list cannot
    distinguish the two (a pure-dict chunk and a mid-chunk dictionary
    fallback both report {PLAIN, RLE, RLE_DICTIONARY}), and the resource
    analyzer must not reduce its peak-HBM ceiling on an unprovable
    claim. None when the headers are unreadable (treated as unproven)."""
    start = getattr(col_meta, "dictionary_page_offset", None)
    if start is None or start <= 0:
        return None
    try:
        end = start + col_meta.total_compressed_size
        with open(path, "rb") as f:
            pos = start
            while pos < end:
                f.seek(pos)
                head = f.read(min(8192, end - pos))
                if not head:
                    break
                r = _Compact(head, 0)
                hdr = r.struct()
                size = hdr[_PH_COMPRESSED]
                kind = hdr[_PH_TYPE]
                if kind == PAGE_DATA_V1:
                    if hdr[_PH_DATA_V1][_DP_ENCODING] not in \
                            (ENC_PLAIN_DICT, ENC_RLE_DICT):
                        return False
                elif kind == PAGE_DATA_V2:
                    if hdr[_PH_DATA_V2][_D2_ENCODING] not in \
                            (ENC_PLAIN_DICT, ENC_RLE_DICT):
                        return False
                elif kind != PAGE_DICT:
                    return False
                pos += r.pos + size
    except Exception:
        return None
    return True


def read_chunk_bytes(path: str, col_meta) -> bytes:
    start = col_meta.dictionary_page_offset
    if start is None or start <= 0:
        start = col_meta.data_page_offset
    with open(path, "rb") as f:
        f.seek(start)
        return f.read(col_meta.total_compressed_size)
