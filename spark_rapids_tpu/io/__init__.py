"""File I/O layer (reference: SURVEY.md section 2.7).

Parquet/ORC/CSV scans and writers. Phase 1 of the SURVEY.md build plan uses
Arrow C++ (via pyarrow) for the host-side decode/encode — the counterpart of
the reference's host-side footer parse + chunk reassembly
(GpuParquetScan.scala:316-458) — feeding the packed single-copy upload into
HBM; moving dictionary/RLE decode into Pallas kernels is a later phase.
"""
