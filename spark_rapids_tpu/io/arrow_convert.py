"""Arrow <-> columnar batch conversion.

The host staging format is Arrow (pyarrow) — its C++ readers play the role
cuDF's native parquet/ORC/CSV decoders play in the reference (GpuParquetScan
/ GpuOrcScan / GpuCSVScan). Conversion is column-at-a-time and zero-copy
where Arrow's layout allows (primitive columns without nulls).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.batch import (
    HostColumnarBatch,
    HostColumnVector,
)
from spark_rapids_tpu.columnar.dtypes import DataType, DecimalType
from spark_rapids_tpu.ops.base import AttributeReference

_ARROW_TO_DT = {
    pa.bool_(): DataType.BOOL,
    pa.int8(): DataType.INT8,
    pa.int16(): DataType.INT16,
    pa.int32(): DataType.INT32,
    pa.int64(): DataType.INT64,
    pa.float32(): DataType.FLOAT32,
    pa.float64(): DataType.FLOAT64,
    pa.string(): DataType.STRING,
    pa.large_string(): DataType.STRING,
    pa.date32(): DataType.DATE,
}


def arrow_type_to_dt(t: pa.DataType) -> DataType:
    if t in _ARROW_TO_DT:
        return _ARROW_TO_DT[t]
    if pa.types.is_timestamp(t):
        return DataType.TIMESTAMP
    if pa.types.is_decimal(t):
        if t.precision > DecimalType.MAX_PRECISION:
            raise TypeError(
                f"decimal precision {t.precision} exceeds the 64-bit cap "
                f"({DecimalType.MAX_PRECISION})")
        return DecimalType(t.precision, t.scale)
    if pa.types.is_dictionary(t):
        return arrow_type_to_dt(t.value_type)
    raise TypeError(f"unsupported arrow type {t} (flat types only, "
                    "reference: GpuOverrides.isSupportedType)")


def dt_to_arrow_type(dt: DataType) -> pa.DataType:
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    for at, d in _ARROW_TO_DT.items():
        if d is dt and at != pa.large_string():
            return at
    if dt is DataType.TIMESTAMP:
        return pa.timestamp("us", tz="UTC")
    raise TypeError(f"no arrow type for {dt}")


def schema_attrs(schema: pa.Schema) -> List[AttributeReference]:
    return [
        AttributeReference(f.name, arrow_type_to_dt(f.type), f.nullable)
        for f in schema
    ]


def _chunked_to_np(col: pa.ChunkedArray) -> pa.Array:
    return col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)


def arrow_to_host_batch(table: pa.Table,
                        attrs: List[AttributeReference]) -> HostColumnarBatch:
    cols = []
    for attr in attrs:
        # look up by NAME: pyarrow ORC returns selected columns in file
        # order, not requested order
        arr = _chunked_to_np(table.column(attr.name))
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        dt = attr.data_type
        n = len(arr)
        validity = np.ones(n, dtype=bool) if arr.null_count == 0 else \
            np.asarray(arr.is_valid())
        if dt is DataType.STRING:
            data = np.empty(n, dtype=object)
            py = arr.to_pylist()
            for i, v in enumerate(py):
                data[i] = v if v is not None else ""
        elif dt is DataType.TIMESTAMP:
            # fill nulls BEFORE to_numpy: arrow otherwise converts through
            # float64/NaT and corrupts large 64-bit values
            a = arr.cast(pa.timestamp("us")).cast(pa.int64()).fill_null(0)
            data = a.to_numpy(zero_copy_only=False).astype(np.int64)
        elif dt is DataType.DATE:
            data = arr.cast(pa.int32()).fill_null(0) \
                .to_numpy(zero_copy_only=False).astype(np.int32)
        elif isinstance(dt, DecimalType):
            data = _decimal_unscaled(arr, dt, validity)
        else:
            npdt = dt.to_np()
            if dt is DataType.BOOL:
                data = arr.fill_null(False).to_numpy(zero_copy_only=False)
            else:
                data = arr.fill_null(npdt.type(0).item()) \
                    .to_numpy(zero_copy_only=False)
            if data.dtype != npdt:
                data = data.astype(npdt)
        cols.append(HostColumnVector(dt, data, validity))
    return HostColumnarBatch(cols, table.num_rows)


def _decimal_unscaled(arr: pa.Array, dt: DecimalType,
                      validity: np.ndarray) -> np.ndarray:
    """decimal128 arrow array -> unscaled int64 (the batch physical form).

    Fast path reads the low 64 bits of each 128-bit little-endian value
    straight from the arrow buffer — exact whenever |unscaled| < 2^63, which
    the p <= 18 gate guarantees."""
    n = len(arr)
    want = pa.decimal128(dt.precision, dt.scale)
    if arr.type != want:
        # the cast raises loudly on values that don't fit dt — never
        # silently truncate a wider column (decimal256, higher precision,
        # other scale) to 64 bits
        arr = arr.cast(want)
    bufs = arr.buffers()
    if len(bufs) > 1 and bufs[1] is not None and np.little_endian:
        raw = np.frombuffer(bufs[1], dtype=np.int64)
        lo = raw[arr.offset * 2:(arr.offset + n) * 2:2].copy()
        return np.where(validity, lo, np.int64(0))
    from spark_rapids_tpu.ops.decimal_util import to_unscaled

    py = arr.to_pylist()
    return np.array(
        [to_unscaled(v, dt.scale) if v is not None else 0 for v in py],
        dtype=np.int64)


def _unscaled_to_decimal128(col, dt: DecimalType) -> pa.Array:
    """Vectorized unscaled int64 -> decimal128 array: widen each value to
    two little-endian 64-bit limbs (lo, sign-extended hi) and hand arrow the
    raw buffer — no per-row Decimal objects."""
    n = len(col.data)
    data = np.ascontiguousarray(col.data[:n], dtype=np.int64)
    validity = np.ascontiguousarray(col.validity[:n], dtype=bool)
    if not np.little_endian:
        from spark_rapids_tpu.ops.decimal_util import from_unscaled

        vals = [from_unscaled(int(v), dt.scale) if ok else None
                for v, ok in zip(data, validity)]
        return pa.array(vals, type=pa.decimal128(dt.precision, dt.scale))
    limbs = np.empty((n, 2), dtype=np.int64)
    limbs[:, 0] = np.where(validity, data, 0)
    limbs[:, 1] = limbs[:, 0] >> 63  # sign extension
    if validity.all():
        vbuf = None
        null_count = 0
    else:
        vbuf = pa.py_buffer(
            np.packbits(validity, bitorder="little").tobytes())
        null_count = int((~validity).sum())
    return pa.Array.from_buffers(
        pa.decimal128(dt.precision, dt.scale), n,
        [vbuf, pa.py_buffer(limbs.tobytes())], null_count=null_count)


def host_batch_to_arrow(batch: HostColumnarBatch,
                        attrs: List[AttributeReference]) -> pa.Table:
    arrays = []
    names = []
    for attr, col in zip(attrs, batch.columns):
        dt = attr.data_type
        mask = ~col.validity  # arrow mask semantics: True = null
        if dt is DataType.STRING:
            vals = [v if ok else None
                    for v, ok in zip(col.data, col.validity)]
            arrays.append(pa.array(vals, type=pa.string()))
        elif dt is DataType.TIMESTAMP:
            arrays.append(pa.array(col.data.astype(np.int64), mask=mask)
                          .cast(pa.timestamp("us", tz="UTC")))
        elif dt is DataType.DATE:
            arrays.append(pa.array(col.data.astype(np.int32), mask=mask)
                          .cast(pa.date32()))
        elif isinstance(dt, DecimalType):
            arrays.append(_unscaled_to_decimal128(col, dt))
        else:
            arrays.append(pa.array(col.data, mask=mask,
                                   type=dt_to_arrow_type(dt)))
        names.append(attr.name)
    # positional construction: duplicate column names must round-trip to the
    # writer (which then raises), not silently drop columns
    return pa.table(arrays, names=names)
