"""Device-side parquet encode (write path).

Reference parity: the reference encodes parquet ON the accelerator into a
host buffer and only streams bytes afterwards (`ColumnarOutputWriter.scala:
62-177` — cudf `Table.writeParquet` under the semaphore,
`GpuParquetFileFormat.scala:34-192`). The TPU-native split mirrors the
device decoder (io/parquet_device.py) in reverse:

- DEVICE (data plane): per column, one jitted kernel compacts the non-null
  values into a dense stream (the PLAIN page payload) and bit-packs the
  validity into v1 definition levels. What downloads is the *encoded* page
  payload — dense values + packed bits — not padded arrays.
- HOST (control plane, tiny): wraps payloads in thrift-compact page
  headers and writes the footer (schema / row group / column chunk
  metadata). No value is touched on the host.

Scope: UNCOMPRESSED PLAIN v1 pages for fixed-width columns (INT32/INT64/
FLOAT/DOUBLE + DATE/TIMESTAMP logical annotations; DECIMAL over INT64).
Files read back with pyarrow/Spark. Strings/bool and compressed output use
the host Arrow writer.
"""

from __future__ import annotations

import functools
import struct
from typing import List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import (
    ColumnarBatch,
    device_float64_supported,
)
from spark_rapids_tpu.columnar.dtypes import DataType, DecimalType

MAGIC = b"PAR1"

# parquet physical type ids (parquet.thrift Type)
_T_INT32 = 1
_T_INT64 = 2
_T_FLOAT = 4
_T_DOUBLE = 5

# ConvertedType ids for logical annotation
_CT_DATE = 6
_CT_TIMESTAMP_MICROS = 10
_CT_DECIMAL = 5


def _phys_type(dt) -> Optional[Tuple[int, int, Optional[int]]]:
    """(parquet physical type, byte width, converted type) or None when the
    dtype can't device-encode."""
    if isinstance(dt, DecimalType):
        return _T_INT64, 8, _CT_DECIMAL
    return {
        DataType.INT32: (_T_INT32, 4, None),
        DataType.INT64: (_T_INT64, 8, None),
        DataType.FLOAT32: (_T_FLOAT, 4, None),
        DataType.FLOAT64: (_T_DOUBLE, 8, None),
        DataType.DATE: (_T_INT32, 4, _CT_DATE),
        DataType.TIMESTAMP: (_T_INT64, 8, _CT_TIMESTAMP_MICROS),
    }.get(dt)


def schema_encodable(attrs) -> bool:
    for a in attrs:
        if _phys_type(a.data_type) is None:
            return False
        if a.data_type is DataType.FLOAT64 and not device_float64_supported():
            return False
    return True


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnums=())
def _encode_fixed(data, validity, num_rows):
    """Compact non-null values to the front (PLAIN payload order) and pack
    validity bits little-endian (v1 def levels). Returns
    (dense_values[cap], packed_bits[cap//8], n_present)."""
    cap = data.shape[0]
    live = validity & (jnp.arange(cap) < num_rows)
    # stable compaction: present rows keep their order
    order = jnp.argsort(~live, stable=True).astype(jnp.int32)
    dense = data[order]
    n_present = jnp.sum(live.astype(jnp.int32))
    bits = live.reshape(cap // 8, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    packed = jnp.sum(bits * weights[None, :], axis=1).astype(jnp.uint8)
    return dense, packed, n_present


def encode_column_page(col, num_rows: int):
    """Device-encode one column of one batch into host page-payload pieces:
    (def_level_bytes, value_bytes, n_present). DOUBLE columns are eligible
    only where the device computes real f64 (schema_encodable gates TPU)."""
    dense, packed, n_present = _encode_fixed(col.data, col.validity,
                                             jnp.int32(num_rows))
    n_present = int(jax.device_get(n_present))
    # slice ON device before download: only the encoded payload transfers
    dense_host = np.asarray(jax.device_get(dense[:n_present]))
    nbytes_bits = (num_rows + 7) // 8
    bits_host = np.asarray(jax.device_get(packed[:nbytes_bits]))
    # v1 def levels: u32 length prefix + RLE-hybrid; ONE bit-packed run of
    # ceil(n/8) groups is always legal
    groups = (num_rows + 7) // 8
    header = _uvarint((groups << 1) | 1)
    dl = header + bits_host.tobytes()
    return struct.pack("<I", len(dl)) + dl, dense_host.tobytes(), n_present


# ---------------------------------------------------------------------------
# Thrift compact writer (just enough for parquet metadata)
# ---------------------------------------------------------------------------
def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(v: int) -> bytes:
    return _uvarint((v << 1) ^ (v >> 63))


class _CompactWriter:
    def __init__(self):
        self.buf = bytearray()
        self._fid_stack: List[int] = []
        self.last_fid = 0

    def _field_header(self, fid: int, ftype: int):
        delta = fid - self.last_fid
        if 0 < delta <= 15:
            self.buf.append((delta << 4) | ftype)
        else:
            self.buf.append(ftype)
            self.buf += _zigzag(fid)
        self.last_fid = fid

    def i32(self, fid: int, v: int):
        self._field_header(fid, 5)
        self.buf += _zigzag(v)

    def i64(self, fid: int, v: int):
        self._field_header(fid, 6)
        self.buf += _zigzag(v)

    def string(self, fid: int, s: str):
        self._field_header(fid, 8)
        b = s.encode("utf-8")
        self.buf += _uvarint(len(b)) + b

    def begin_struct(self, fid: int):
        self._field_header(fid, 12)
        self._fid_stack.append(self.last_fid)
        self.last_fid = 0

    def begin_element_struct(self):
        """A struct that is a LIST ELEMENT: no field header byte — compact
        protocol list elements are bare values."""
        self._fid_stack.append(self.last_fid)
        self.last_fid = 0

    def end_struct(self):
        self.buf.append(0)
        self.last_fid = self._fid_stack.pop()

    def list_header(self, fid: int, etype: int, n: int):
        self._field_header(fid, 9)
        if n < 15:
            self.buf.append((n << 4) | etype)
        else:
            self.buf.append(0xF0 | etype)
            self.buf += _uvarint(n)

    def stop(self) -> bytes:
        self.buf.append(0)
        return bytes(self.buf)


def _page_header(n_values: int, payload_len: int) -> bytes:
    w = _CompactWriter()
    w.i32(1, 0)                    # type = DATA_PAGE
    w.i32(2, payload_len)          # uncompressed_size
    w.i32(3, payload_len)          # compressed_size
    w.begin_struct(5)              # data_page_header
    w.i32(1, n_values)
    w.i32(2, 0)                    # encoding = PLAIN
    w.i32(3, 3)                    # definition_level_encoding = RLE
    w.i32(4, 3)                    # repetition_level_encoding = RLE
    w.end_struct()
    return w.stop()


def _schema_element(w: _CompactWriter, a) -> None:
    phys, _width, conv = _phys_type(a.data_type)
    w.begin_element_struct()
    w.i32(1, phys)
    w.i32(3, 1)        # repetition = OPTIONAL
    w.string(4, a.name)
    if conv is not None:
        w.i32(6, conv)
    if isinstance(a.data_type, DecimalType):
        w.i32(7, a.data_type.scale)
        w.i32(8, a.data_type.precision)
    w.end_struct()


def write_file(path: str, attrs, batches: List[ColumnarBatch]) -> int:
    """Assemble one parquet file from device-encoded pages. Returns rows
    written."""
    # encode: pages[column][batch] -> (def_bytes, val_bytes, n_present, n)
    pages: List[List[Tuple[bytes, bytes, int, int]]] = [[] for _ in attrs]
    total_rows = 0
    for b in batches:
        for ci, a in enumerate(attrs):
            defb, valb, npres = encode_column_page(b.columns[ci],
                                                   b.num_rows)
            pages[ci].append((defb, valb, npres, b.num_rows))
        total_rows += b.num_rows
    with open(path, "wb") as f:
        f.write(MAGIC)
        offset = 4
        col_meta = []
        for ci, a in enumerate(attrs):
            first_off = offset
            n_vals = 0
            chunk_bytes = 0
            for defb, valb, npres, nrows in pages[ci]:
                payload = defb + valb
                hdr = _page_header(nrows, len(payload))
                f.write(hdr)
                f.write(payload)
                offset += len(hdr) + len(payload)
                chunk_bytes += len(hdr) + len(payload)
                n_vals += nrows
            col_meta.append((a, first_off, n_vals, chunk_bytes))
        # footer: FileMetaData
        w = _CompactWriter()
        w.i32(1, 1)                          # version
        w.list_header(2, 12, len(attrs) + 1)  # schema
        # root schema element
        w.begin_element_struct()
        w.string(4, "schema")
        w.i32(5, len(attrs))                 # num_children
        w.end_struct()
        for a in attrs:
            _schema_element(w, a)
        w.i64(3, total_rows)                 # num_rows
        w.list_header(4, 12, 1)              # row_groups
        w.begin_element_struct()             # RowGroup
        w.list_header(1, 12, len(attrs))     # columns
        for a, first_off, n_vals, chunk_bytes in col_meta:
            w.begin_element_struct()         # ColumnChunk
            w.i64(2, first_off)              # file_offset
            w.begin_struct(3)                # ColumnMetaData
            w.i32(1, _phys_type(a.data_type)[0])
            w.list_header(2, 5, 2)           # encodings [PLAIN, RLE]
            w.buf += _zigzag(0) + _zigzag(3)
            w.list_header(3, 8, 1)           # path_in_schema
            nb = a.name.encode("utf-8")
            w.buf += _uvarint(len(nb)) + nb
            w.i32(4, 0)                      # codec = UNCOMPRESSED
            w.i64(5, n_vals)
            w.i64(6, chunk_bytes)            # total_uncompressed_size
            w.i64(7, chunk_bytes)            # total_compressed_size
            w.i64(9, first_off)              # data_page_offset
            w.end_struct()
            w.end_struct()
        w.i64(2, sum(m[3] for m in col_meta))  # total_byte_size
        w.i64(3, total_rows)                   # num_rows
        w.end_struct()
        w.string(6, "spark-rapids-tpu device encoder")
        footer = w.stop()
        f.write(footer)
        f.write(struct.pack("<I", len(footer)))
        f.write(MAGIC)
    return total_rows
