"""Device-side parquet encode (write path).

Reference parity: the reference encodes parquet ON the accelerator into a
host buffer and only streams bytes afterwards (`ColumnarOutputWriter.scala:
62-177` — cudf `Table.writeParquet` under the semaphore,
`GpuParquetFileFormat.scala:34-192`). The TPU-native split mirrors the
device decoder (io/parquet_device.py) in reverse:

- DEVICE (data plane): per column, one jitted kernel compacts the non-null
  values into a dense stream (the PLAIN page payload) and bit-packs the
  validity into v1 definition levels. What downloads is the *encoded* page
  payload — dense values + packed bits — not padded arrays.
- HOST (control plane, tiny): wraps payloads in thrift-compact page
  headers and writes the footer (schema / row group / column chunk
  metadata). No value is touched on the host.

Scope: PLAIN v1 pages for fixed-width columns (INT32/INT64/FLOAT/DOUBLE +
DATE/TIMESTAMP logical annotations; DECIMAL over INT64), STRING
(BYTE_ARRAY with device-built length prefixes), and BOOLEAN (dense
values bit-packed LSB-first). Pages optionally host-compressed per block
(snappy/gzip/zstd via the same pyarrow codecs the decoder uses — the
exact mirror of the decode split: device data plane, host block codec).
Files read back with pyarrow/Spark. Nested types use the host Arrow
writer.
"""

from __future__ import annotations

import functools
import struct
from typing import List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import (
    ColumnarBatch,
    device_float64_supported,
)
from spark_rapids_tpu.columnar.dtypes import DataType, DecimalType

MAGIC = b"PAR1"

# parquet physical type ids (parquet.thrift Type)
_T_BOOLEAN = 0
_T_INT32 = 1
_T_INT64 = 2
_T_FLOAT = 4
_T_DOUBLE = 5
_T_BYTE_ARRAY = 6

# ConvertedType ids for logical annotation
_CT_UTF8 = 0
_CT_DATE = 6
_CT_TIMESTAMP_MICROS = 10
_CT_DECIMAL = 5

# parquet CompressionCodec ids and the pyarrow codec names behind them
_CODECS = {"UNCOMPRESSED": (0, None), "SNAPPY": (1, "snappy"),
           "GZIP": (2, "gzip"), "ZSTD": (6, "zstd")}


def _phys_type(dt) -> Optional[Tuple[int, int, Optional[int]]]:
    """(parquet physical type, byte width, converted type) or None when the
    dtype can't device-encode."""
    if isinstance(dt, DecimalType):
        return _T_INT64, 8, _CT_DECIMAL
    return {
        DataType.INT32: (_T_INT32, 4, None),
        DataType.INT64: (_T_INT64, 8, None),
        DataType.FLOAT32: (_T_FLOAT, 4, None),
        DataType.FLOAT64: (_T_DOUBLE, 8, None),
        DataType.DATE: (_T_INT32, 4, _CT_DATE),
        DataType.TIMESTAMP: (_T_INT64, 8, _CT_TIMESTAMP_MICROS),
        DataType.STRING: (_T_BYTE_ARRAY, 0, _CT_UTF8),
        DataType.BOOL: (_T_BOOLEAN, 0, None),
    }.get(dt)


def schema_encodable(attrs) -> bool:
    for a in attrs:
        if _phys_type(a.data_type) is None:
            return False
        if a.data_type is DataType.FLOAT64 and not device_float64_supported():
            return False
    return True


def codec_supported(compression: str) -> bool:
    """Can the device encoder produce this parquet compression? (Mirrors
    the decoder's host block-codec support, parquet_device.py.)"""
    name = compression.upper()
    if name in ("NONE",):
        name = "UNCOMPRESSED"
    if name not in _CODECS:
        return False
    cid, pa_name = _CODECS[name]
    if pa_name is None:
        return True
    try:
        import pyarrow as pa

        pa.Codec(pa_name)
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnums=())
def _encode_fixed(data, validity, num_rows):
    """Compact non-null values to the front (PLAIN payload order) and pack
    validity bits little-endian (v1 def levels). Returns
    (dense_values[cap], packed_bits[cap//8], n_present)."""
    cap = data.shape[0]
    live = validity & (jnp.arange(cap) < num_rows)
    # stable compaction: present rows keep their order
    order = jnp.argsort(~live, stable=True).astype(jnp.int32)
    dense = data[order]
    n_present = jnp.sum(live.astype(jnp.int32))
    bits = live.reshape(cap // 8, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    packed = jnp.sum(bits * weights[None, :], axis=1).astype(jnp.uint8)
    return dense, packed, n_present


@functools.partial(jax.jit, static_argnums=(4, 5))
def _encode_string_plan(data, offsets, validity, num_rows, cap: int,
                        prefix: int = 4):
    """Plan a dense string byte stream: per present row the output is
    [prefix length bytes][bytes] (prefix=4 -> parquet BYTE_ARRAY PLAIN;
    prefix=0 -> ORC DATA stream). Returns (sel_rows, out_lens,
    out_offsets, n_present, total_bytes) with sel = dense non-null row
    ids in order."""
    live = validity & (jnp.arange(cap) < num_rows)
    order = jnp.argsort(~live, stable=True).astype(jnp.int32)
    n_present = jnp.sum(live.astype(jnp.int32))
    sel = order
    lens = (offsets[1:] - offsets[:-1])[sel]
    in_sel = jnp.arange(cap) < n_present
    piece = jnp.where(in_sel, lens + prefix, 0)
    out_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), jnp.cumsum(piece, dtype=jnp.int32)])
    return sel, lens, out_offsets, n_present, out_offsets[-1]


@functools.partial(jax.jit, static_argnums=(5, 6))
def _encode_string_bytes(data, offsets, sel, lens, out_offsets,
                         byte_cap: int, prefix: int = 4):
    """Materialize the (optionally length-prefixed) dense byte stream in
    ONE kernel: each output byte is either a little-endian length byte
    (first `prefix` of its value) or a gathered source byte."""
    cap = sel.shape[0]
    pos = jnp.arange(byte_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(out_offsets[1:], pos, side="right"),
                   0, cap - 1).astype(jnp.int32)
    within = pos - out_offsets[row]
    src_start = offsets[:-1][sel]
    src_pos = jnp.clip(src_start[row] + within - prefix, 0,
                       data.shape[0] - 1)
    valid = pos < out_offsets[-1]
    if prefix:
        is_len = within < prefix
        ln = lens[row].astype(jnp.uint32)
        len_byte = (ln >> (within.astype(jnp.uint32) * 8)) & \
            jnp.uint32(0xFF)
        out = jnp.where(is_len, len_byte.astype(jnp.uint8), data[src_pos])
    else:
        out = data[src_pos]
    return jnp.where(valid, out, 0).astype(jnp.uint8)


def encode_column_page(col, num_rows: int):
    """Device-encode one column of one batch into host page-payload pieces:
    (def_level_bytes, value_bytes, n_present). DOUBLE columns are eligible
    only where the device computes real f64 (schema_encodable gates TPU)."""
    from spark_rapids_tpu.columnar.batch import bucket_capacity
    from spark_rapids_tpu.columnar.dtypes import DataType as _DT

    if col.dtype is _DT.STRING:
        cap = col.validity.shape[0]
        sel, lens, out_offsets, n_present, total = _encode_string_plan(
            col.data, col.offsets, col.validity, jnp.int32(num_rows), cap)
        n_present = int(jax.device_get(n_present))
        total = int(jax.device_get(total))
        byte_cap = bucket_capacity(max(total, 1))
        stream = _encode_string_bytes(col.data, col.offsets, sel, lens,
                                      out_offsets, byte_cap)
        val_host = np.asarray(jax.device_get(stream[:total]))
        packed = _pack_validity_bits(col.validity, jnp.int32(num_rows))
        nbytes_bits = (num_rows + 7) // 8
        bits_host = np.asarray(jax.device_get(packed[:nbytes_bits]))
        groups = (num_rows + 7) // 8
        header = _uvarint((groups << 1) | 1)
        dl = header + bits_host.tobytes()
        return (struct.pack("<I", len(dl)) + dl, val_host.tobytes(),
                n_present)
    dense, packed, n_present = _encode_fixed(col.data, col.validity,
                                             jnp.int32(num_rows))
    n_present = int(jax.device_get(n_present))
    if col.dtype is _DT.BOOL:
        # PLAIN booleans: dense values bit-packed LSB-first
        vbits = _pack_validity_bits(dense.astype(bool),
                                    jnp.int32(n_present))
        val_host = np.asarray(
            jax.device_get(vbits[:(n_present + 7) // 8]))
        dense_host = None
    else:
        # slice ON device before download: only the encoded payload
        # transfers
        dense_host = np.asarray(jax.device_get(dense[:n_present]))
    nbytes_bits = (num_rows + 7) // 8
    bits_host = np.asarray(jax.device_get(packed[:nbytes_bits]))
    # v1 def levels: u32 length prefix + RLE-hybrid; ONE bit-packed run of
    # ceil(n/8) groups is always legal
    groups = (num_rows + 7) // 8
    header = _uvarint((groups << 1) | 1)
    dl = header + bits_host.tobytes()
    vals = (val_host if dense_host is None else dense_host).tobytes()
    return struct.pack("<I", len(dl)) + dl, vals, n_present


@jax.jit
def _pack_validity_bits(validity, num_rows):
    cap = validity.shape[0]
    live = validity & (jnp.arange(cap) < num_rows)
    bits = live.reshape(cap // 8, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(bits * weights[None, :], axis=1).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Thrift compact writer (just enough for parquet metadata)
# ---------------------------------------------------------------------------
def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(v: int) -> bytes:
    return _uvarint((v << 1) ^ (v >> 63))


class _CompactWriter:
    def __init__(self):
        self.buf = bytearray()
        self._fid_stack: List[int] = []
        self.last_fid = 0

    def _field_header(self, fid: int, ftype: int):
        delta = fid - self.last_fid
        if 0 < delta <= 15:
            self.buf.append((delta << 4) | ftype)
        else:
            self.buf.append(ftype)
            self.buf += _zigzag(fid)
        self.last_fid = fid

    def i32(self, fid: int, v: int):
        self._field_header(fid, 5)
        self.buf += _zigzag(v)

    def i64(self, fid: int, v: int):
        self._field_header(fid, 6)
        self.buf += _zigzag(v)

    def string(self, fid: int, s: str):
        self._field_header(fid, 8)
        b = s.encode("utf-8")
        self.buf += _uvarint(len(b)) + b

    def begin_struct(self, fid: int):
        self._field_header(fid, 12)
        self._fid_stack.append(self.last_fid)
        self.last_fid = 0

    def begin_element_struct(self):
        """A struct that is a LIST ELEMENT: no field header byte — compact
        protocol list elements are bare values."""
        self._fid_stack.append(self.last_fid)
        self.last_fid = 0

    def end_struct(self):
        self.buf.append(0)
        self.last_fid = self._fid_stack.pop()

    def list_header(self, fid: int, etype: int, n: int):
        self._field_header(fid, 9)
        if n < 15:
            self.buf.append((n << 4) | etype)
        else:
            self.buf.append(0xF0 | etype)
            self.buf += _uvarint(n)

    def stop(self) -> bytes:
        self.buf.append(0)
        return bytes(self.buf)


def _page_header(n_values: int, payload_len: int,
                 compressed_len: int) -> bytes:
    w = _CompactWriter()
    w.i32(1, 0)                    # type = DATA_PAGE
    w.i32(2, payload_len)          # uncompressed_size
    w.i32(3, compressed_len)       # compressed_size
    w.begin_struct(5)              # data_page_header
    w.i32(1, n_values)
    w.i32(2, 0)                    # encoding = PLAIN
    w.i32(3, 3)                    # definition_level_encoding = RLE
    w.i32(4, 3)                    # repetition_level_encoding = RLE
    w.end_struct()
    return w.stop()


def _schema_element(w: _CompactWriter, a) -> None:
    phys, _width, conv = _phys_type(a.data_type)
    w.begin_element_struct()
    w.i32(1, phys)
    w.i32(3, 1)        # repetition = OPTIONAL
    w.string(4, a.name)
    if conv is not None:
        w.i32(6, conv)
    if isinstance(a.data_type, DecimalType):
        w.i32(7, a.data_type.scale)
        w.i32(8, a.data_type.precision)
    w.end_struct()


def write_file(path: str, attrs, batches: List[ColumnarBatch],
               compression: str = "UNCOMPRESSED") -> int:
    """Assemble one parquet file from device-encoded pages; page payloads
    are host-block-compressed when a codec is requested (the exact mirror
    of the decode split — device data plane, host block codec). Returns
    rows written."""
    cname = compression.upper()
    if cname == "NONE":
        cname = "UNCOMPRESSED"
    codec_id, pa_name = _CODECS[cname]
    pa_codec = None
    if pa_name is not None:
        import pyarrow as pa

        pa_codec = pa.Codec(pa_name)
    from spark_rapids_tpu.columnar.batch import ensure_compact

    # encode: pages[column][batch] -> (def_bytes, val_bytes, n_present, n)
    pages: List[List[Tuple[bytes, bytes, int, int]]] = [[] for _ in attrs]
    total_rows = 0
    for b in batches:
        # live-masked batches (exchange outputs) compact first: validity
        # and offsets must be positional over the rows actually written
        b = ensure_compact(b)
        for ci, a in enumerate(attrs):
            defb, valb, npres = encode_column_page(b.columns[ci],
                                                   b.num_rows)
            pages[ci].append((defb, valb, npres, b.num_rows))
        total_rows += b.num_rows
    with open(path, "wb") as f:
        f.write(MAGIC)
        offset = 4
        col_meta = []
        for ci, a in enumerate(attrs):
            first_off = offset
            n_vals = 0
            chunk_bytes = 0
            chunk_raw_bytes = 0
            for defb, valb, npres, nrows in pages[ci]:
                payload = defb + valb
                if pa_codec is not None:
                    wire = bytes(pa_codec.compress(payload))
                else:
                    wire = payload
                hdr = _page_header(nrows, len(payload), len(wire))
                f.write(hdr)
                f.write(wire)
                offset += len(hdr) + len(wire)
                chunk_bytes += len(hdr) + len(wire)
                chunk_raw_bytes += len(hdr) + len(payload)
                n_vals += nrows
            col_meta.append((a, first_off, n_vals, chunk_bytes,
                             chunk_raw_bytes))
        # footer: FileMetaData
        w = _CompactWriter()
        w.i32(1, 1)                          # version
        w.list_header(2, 12, len(attrs) + 1)  # schema
        # root schema element
        w.begin_element_struct()
        w.string(4, "schema")
        w.i32(5, len(attrs))                 # num_children
        w.end_struct()
        for a in attrs:
            _schema_element(w, a)
        w.i64(3, total_rows)                 # num_rows
        w.list_header(4, 12, 1)              # row_groups
        w.begin_element_struct()             # RowGroup
        w.list_header(1, 12, len(attrs))     # columns
        for a, first_off, n_vals, chunk_bytes, chunk_raw in col_meta:
            w.begin_element_struct()         # ColumnChunk
            w.i64(2, first_off)              # file_offset
            w.begin_struct(3)                # ColumnMetaData
            w.i32(1, _phys_type(a.data_type)[0])
            w.list_header(2, 5, 2)           # encodings [PLAIN, RLE]
            w.buf += _zigzag(0) + _zigzag(3)
            w.list_header(3, 8, 1)           # path_in_schema
            nb = a.name.encode("utf-8")
            w.buf += _uvarint(len(nb)) + nb
            w.i32(4, codec_id)               # codec
            w.i64(5, n_vals)
            w.i64(6, chunk_raw)              # total_uncompressed_size
            w.i64(7, chunk_bytes)            # total_compressed_size
            w.i64(9, first_off)              # data_page_offset
            w.end_struct()
            w.end_struct()
        w.i64(2, sum(m[3] for m in col_meta))  # total_byte_size
        w.i64(3, total_rows)                   # num_rows
        w.end_struct()
        w.string(6, "spark-rapids-tpu device encoder")
        footer = w.stop()
        f.write(footer)
        f.write(struct.pack("<I", len(footer)))
        f.write(MAGIC)
    return total_rows
