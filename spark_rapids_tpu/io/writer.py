"""File writers (reference: ColumnarOutputWriter.scala, GpuParquetFileFormat,
GpuOrcFileFormat, GpuFileFormatWriter/GpuFileFormatDataWriter).

Reference parity:
- per-partition part files + _SUCCESS marker and save-mode handling
  (GpuFileFormatWriter.scala / GpuInsertIntoHadoopFsRelationCommand) ->
  `execute_write`.
- dynamic partitioning by partition columns into key=value directories
  (GpuFileFormatDataWriter dynamic writer, 417 LoC) -> `_write_partitioned`.

Eligible schemas encode ON DEVICE (io/parquet_encode_device.py /
io/orc_encode_device.py — the reference encodes on-GPU via cudf
Table.writeParquet/writeORC into a host buffer, ColumnarOutputWriter.
scala:62-177) with host block compression; everything else encodes on
the host with Arrow C++ after the device->host boundary.
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Any, Dict, List

import numpy as np

from spark_rapids_tpu.columnar.batch import HostColumnarBatch
from spark_rapids_tpu.io.arrow_convert import host_batch_to_arrow
from spark_rapids_tpu.ops.base import AttributeReference
from spark_rapids_tpu.plan import logical as L


class WriteError(RuntimeError):
    pass


def execute_write(session, plan: L.WriteFile) -> None:
    path = plan.path
    if os.path.exists(path):
        if plan.mode == "error":
            raise WriteError(
                f"path {path} already exists (mode=error[ifexists])")
        if plan.mode == "ignore":
            return
        if plan.mode == "overwrite":
            shutil.rmtree(path, ignore_errors=True)
    os.makedirs(path, exist_ok=True)

    child = plan.children[0]
    attrs = child.output
    physical = session._physical_plan(child)

    # optional sort after hash ops so written files cluster equal keys
    # (reference: GpuTransitionOverrides.insertHashOptimizeSorts :171-204)
    from spark_rapids_tpu.plan.transition_overrides import (
        insert_hash_optimize_sort,
    )

    physical = insert_hash_optimize_sort(physical, session.conf)

    # Device-side parquet encode (reference: ColumnarOutputWriter.scala:
    # 62-177 encodes on the accelerator): peel the root DeviceToHost
    # transition and hand DEVICE batches to the device encoder — what
    # downloads is the encoded page payload, not padded columns.
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.exec.transitions import DeviceToHostExec
    from spark_rapids_tpu.io import parquet_encode_device as PE

    # device encode + host block compression mirrors the decode split:
    # the DEFAULT snappy parquet write goes through the device encoder
    # (reference behavior: ColumnarOutputWriter.scala:62-177 encodes
    # compressed parquet/ORC on the accelerator)
    from spark_rapids_tpu.io import orc_encode_device as OE

    part_names = list(plan.partition_by or [])
    data_attrs_w = [a for a in attrs if a.name not in part_names]
    pq_compression = str(plan.options.get("compression", "snappy")).lower()
    device_encode = (
        plan.fmt == "parquet"
        and session.conf.get(C.PARQUET_DEVICE_ENCODE)
        and PE.codec_supported(pq_compression)
        and isinstance(physical, DeviceToHostExec)
        and PE.schema_encodable(data_attrs_w))
    orc_compression = str(plan.options.get("compression",
                                           "uncompressed")).lower()
    device_encode_orc = (
        plan.fmt == "orc"
        and not plan.partition_by
        and session.conf.get(C.ORC_DEVICE_ENCODE)
        and OE.codec_supported(orc_compression)
        and isinstance(physical, DeviceToHostExec)
        and OE.schema_encodable(attrs))
    if device_encode or device_encode_orc:
        physical = physical.children[0]

    ctx = session._exec_context()
    pb = physical.execute(ctx)
    write_id = uuid.uuid4().hex[:12]

    def write_partition(pidx: int) -> int:
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.encoded import decode_batch

        # the device encoders read raw (offsets, bytes) string layouts:
        # encoded columns decode at the writer boundary
        batches = [decode_batch(b) if isinstance(b, ColumnarBatch) else b
                   for b in pb.iterator(pidx) if b.num_rows > 0]
        if not batches:
            return 0
        if device_encode and plan.partition_by:
            return _write_partitioned_device(
                batches, attrs, plan, path, pidx, write_id,
                pq_compression)
        if device_encode:
            fname = f"part-{pidx:05d}-{write_id}.{_ext(plan.fmt)}"
            return PE.write_file(os.path.join(path, fname), attrs, batches,
                                 compression=pq_compression)
        if device_encode_orc:
            fname = f"part-{pidx:05d}-{write_id}.{_ext(plan.fmt)}"
            return OE.write_file(os.path.join(path, fname), attrs, batches,
                                 compression=orc_compression)
        if plan.partition_by:
            return _write_partitioned(batches, attrs, plan, path, pidx,
                                      write_id)
        table = _concat_arrow(batches, attrs)
        fname = f"part-{pidx:05d}-{write_id}.{_ext(plan.fmt)}"
        _write_table(table, os.path.join(path, fname), plan)
        return table.num_rows

    session.scheduler.run_job(pb.num_partitions, write_partition)
    with open(os.path.join(path, "_SUCCESS"), "w"):
        pass


def _ext(fmt: str) -> str:
    return {"parquet": "parquet", "orc": "orc", "csv": "csv"}[fmt]


def _concat_arrow(batches: List[HostColumnarBatch], attrs):
    import pyarrow as pa

    tables = [host_batch_to_arrow(b, attrs) for b in batches]
    return tables[0] if len(tables) == 1 else pa.concat_tables(tables)


def _write_table(table, file_path: str, plan: L.WriteFile) -> None:
    if plan.fmt == "parquet":
        import pyarrow.parquet as pq

        compression = plan.options.get("compression", "snappy")
        pq.write_table(table, file_path, compression=compression)
    elif plan.fmt == "orc":
        import pyarrow.orc as po

        po.write_table(table, file_path)
    elif plan.fmt == "csv":
        import pyarrow.csv as pc

        header = plan.options.get("header", True)
        from spark_rapids_tpu.io.scan import _to_bool

        pc.write_csv(
            table, file_path,
            write_options=pc.WriteOptions(
                include_header=_to_bool(header),
                delimiter=plan.options.get("sep", ",")))
    else:
        raise ValueError(f"unknown write format {plan.fmt}")


def _write_partitioned_device(batches, attrs, plan, path: str, pidx: int,
                              write_id: str, compression: str) -> int:
    """Dynamic-partition write with DEVICE encode (reference: the dynamic
    partition data writer encodes on the accelerator,
    GpuFileFormatDataWriter.scala): only the partition-KEY columns come to
    the host (they name the directories), the data columns group on
    device — one route dispatch + one per-group range gather per batch —
    and each group's device batch runs the existing parquet device
    encoder."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.batch import (
        ColumnarBatch,
        bucket_capacity,
        gather_batch,
    )
    from spark_rapids_tpu.io import parquet_encode_device as PE
    from spark_rapids_tpu.shuffle.exchange import _route_plan, _slice_indices

    part_names = plan.partition_by
    part_idx = [i for i, a in enumerate(attrs) if a.name in part_names]
    data_idx = [i for i, a in enumerate(attrs) if a.name not in part_names]
    data_attrs = [attrs[i] for i in data_idx]
    from spark_rapids_tpu.columnar.batch import ensure_compact

    groups: Dict[tuple, List] = {}
    for b in batches:
        # live-masked shuffle/ici views hold real rows in scattered lanes;
        # the key download and the group routing below address physical
        # lanes 0..n-1, so compact first
        b = ensure_compact(b)
        n = b.host_rows()
        # 1. keys to host (small: the partition columns only)
        key_host = ColumnarBatch([b.columns[i] for i in part_idx],
                                 n).to_host()
        key_vals, inverse, first_idx = _partition_key_groups(
            key_host.columns, n)
        # 2. route data rows by group id on device (contiguous ranges)
        n_groups = len(first_idx)
        gid = np.full(bucket_capacity(max(n, 1)), n_groups, np.int32)
        gid[:n] = inverse.astype(np.int32)
        order, counts_dev = _route_plan(jnp.asarray(gid), n_groups)
        counts = np.asarray(jax.device_get(counts_dev))
        data_batch = ColumnarBatch([b.columns[i] for i in data_idx], n)
        offset = 0
        for g in range(n_groups):
            c = int(counts[g])
            if c == 0:
                continue
            idx = _slice_indices(order, np.int32(offset),
                                 bucket_capacity(max(c, 1)))
            piece = gather_batch(data_batch, idx, c, unique_indices=True)
            key = tuple(kv[first_idx[g]] for kv in key_vals)
            groups.setdefault(key, []).append(piece)
            offset += c
    total = 0
    seq = 0
    for key, gbatches in groups.items():
        out_dir = os.path.join(path, _partition_dirname(attrs, part_idx,
                                                        key))
        os.makedirs(out_dir, exist_ok=True)
        fname = f"part-{pidx:05d}-{seq:03d}-{write_id}.{_ext(plan.fmt)}"
        total += PE.write_file(os.path.join(out_dir, fname), data_attrs,
                               gbatches, compression=compression)
        seq += 1
    return total


def _partition_key_groups(key_cols, n: int):
    """Canonical partition-key grouping shared by the device- and
    host-encoded dynamic writers: (per-column value arrays with None for
    NULL, per-row group index, each group's first row index)."""
    key_vals = [np.where(c.validity, c.data.astype(object), None)
                for c in key_cols]
    decorated = np.array(
        ["\x00".join(repr(kv[i]) for kv in key_vals) for i in range(n)],
        dtype=object)
    _uniq, first_idx, inverse = np.unique(
        decorated, return_index=True, return_inverse=True)
    return key_vals, inverse, first_idx


def _partition_dirname(attrs, part_idx, key) -> str:
    return "/".join(f"{attrs[i].name}={_part_value(v)}"
                    for i, v in zip(part_idx, key))


def _write_partitioned(batches: List[HostColumnarBatch], attrs, plan,
                       path: str, pidx: int, write_id: str) -> int:
    """Hive-style key=value directory layout (reference: the dynamic
    partition data writer, GpuFileFormatDataWriter.scala)."""
    from spark_rapids_tpu.columnar.batch import HostColumnVector

    part_names = plan.partition_by
    part_idx = [i for i, a in enumerate(attrs) if a.name in part_names]
    data_idx = [i for i, a in enumerate(attrs) if a.name not in part_names]
    data_attrs = [attrs[i] for i in data_idx]
    total = 0
    seq = 0
    # vectorized grouping per batch: unique over decorated key strings ->
    # per-group boolean masks; no per-row python loops over the data
    groups: Dict[tuple, List[HostColumnarBatch]] = {}
    for b in batches:
        key_vals, inverse, first_idx = _partition_key_groups(
            [b.columns[i] for i in part_idx], b.num_rows)
        for g in range(len(first_idx)):
            mask = inverse == g
            key = tuple(kv[first_idx[g]] for kv in key_vals)
            cols = [
                HostColumnVector(attrs[i].data_type,
                                 b.columns[i].data[mask],
                                 b.columns[i].validity[mask])
                for i in data_idx
            ]
            groups.setdefault(key, []).append(
                HostColumnarBatch(cols, int(mask.sum())))
    for key, group_batches in groups.items():
        out_dir = os.path.join(path, _partition_dirname(attrs, part_idx,
                                                        key))
        os.makedirs(out_dir, exist_ok=True)
        table = _concat_arrow(group_batches, data_attrs)
        fname = f"part-{pidx:05d}-{seq:03d}-{write_id}.{_ext(plan.fmt)}"
        _write_table(table, os.path.join(out_dir, fname), plan)
        seq += 1
        total += table.num_rows
    return total


def _part_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    if isinstance(v, np.generic):
        v = v.item()
    # escape path-hostile characters the way Spark's escapePathName does
    from urllib.parse import quote

    s = str(v)
    escaped = quote(s, safe=" :+-_.,")
    return escaped if escaped else "__EMPTY__"
