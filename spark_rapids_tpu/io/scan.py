"""File scan execs (reference: GpuParquetScan.scala, GpuOrcScan.scala,
GpuBatchScanExec.scala CSV).

Reference parity:
- read-partition planning by row-group/row-count caps
  (populateCurrentBlockChunk, GpuParquetScan.scala:571-605;
  maxReadBatchSizeRows/Bytes, RapidsConf.scala:315-322) -> `plan_splits`.
- host-side read + device upload with task admission
  (semaphore acquire before decode/upload, GpuParquetScan.scala:300,554) ->
  `TpuFileScanExec` host-decodes via Arrow C++ then does the packed
  single-copy upload under the TpuSemaphore.
- per-format enable confs (RapidsConf.scala:433-469) -> tagged in
  plan/overrides.py.

Phase 1 decodes on the host with Arrow C++ (the correctness oracle the
SURVEY.md build plan keeps); phase 2+ moves Parquet dictionary/RLE decode
into Pallas kernels fed by raw column chunks.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.batch import HostColumnarBatch, HostColumnVector
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import (
    CpuExec,
    ExecContext,
    PartitionedBatches,
    PhysicalExec,
    TpuExec,
    count_output,
)
from spark_rapids_tpu.exec.transitions import current_task_id
from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.ops.base import AttributeReference
from spark_rapids_tpu.utils import metrics as M


@dataclass(frozen=True)
class FileSplit:
    """One read task: a file plus (for parquet) the row groups to read.
    `partition_values` carries the Hive-style key=value directory components
    of the file's path (reference: PartitionedFile partitionValues appended
    by ColumnarPartitionReaderWithPartitionValues)."""

    path: str
    fmt: str
    row_groups: Optional[Tuple[int, ...]] = None
    options: Tuple[Tuple[str, Any], ...] = ()
    partition_values: Tuple[Tuple[str, Optional[str]], ...] = ()

    def opt(self, key: str, default=None):
        return dict(self.options).get(key, default)


HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _orc_stats_vrange(attr, meta) -> Optional[Tuple[int, int]]:
    """(lo, hi) for an ORC column from the file footer's IntegerStatistics
    (parsed in orc_device.parse_file_meta), INT64 columns only — the same
    narrowing proof _pq_stats_vrange supplies for parquet."""
    from spark_rapids_tpu.columnar.batch import (
        int64_narrowing_enabled,
        quantize_vrange,
    )

    if attr.data_type is not DataType.INT64 or not int64_narrowing_enabled():
        return None
    try:
        cid = meta.names.index(attr.name)
        if 0 <= cid < len(meta.col_stats):
            st = meta.col_stats[cid]
            if (isinstance(st, tuple) and len(st) == 2
                    and all(isinstance(x, int) for x in st)):
                return quantize_vrange(st)
    except (ValueError, AttributeError):
        pass
    return None


def _stack_minmax(reds):
    """Stack per-column (any_valid, lo, hi) scalars into one [n, 3] int64
    array so the verify fetch is a single host round trip."""
    from spark_rapids_tpu.engine.jit_cache import get_or_build

    def build():
        import jax
        import jax.numpy as jnp

        def fn(rs):
            return jnp.stack([
                jnp.stack([a.astype(jnp.int64), lo.astype(jnp.int64),
                           hi.astype(jnp.int64)])
                for a, lo, hi in rs])
        return jax.jit(fn)

    return get_or_build(("scan_minmax_stack", len(reds)), build)(reds)


def _minmax_valid(data, validity):
    """(any_valid, min, max) over valid lanes — jitted via the process cache
    so every int64 column shares one compiled reduction per shape bucket."""
    from spark_rapids_tpu.engine.jit_cache import get_or_build

    def build():
        import jax
        import jax.numpy as jnp

        def fn(d, v):
            lo = jnp.min(jnp.where(v, d, jnp.iinfo(d.dtype).max))
            hi = jnp.max(jnp.where(v, d, jnp.iinfo(d.dtype).min))
            return jnp.any(v), lo, hi
        return jax.jit(fn)

    return get_or_build(("scan_minmax_valid",), build)(data, validity)


def verify_footer_vranges(dev_cols: Dict[str, "ColumnVector"]) -> List[str]:
    """Check footer-statistics-derived value ranges against the decoded
    data before any consumer narrows on them. Writers have shipped corrupt
    min/max stats (parquet-mr carries CorruptStatistics heuristics for
    exactly this); unlike row-group pruning — where a bad stat only loses
    pruning — a bad range here would silently WRAP int32-narrowed values.
    One batched reduction + one host transfer covers every claimed column
    of the row group/stripe; a violated claim drops the vrange (the file
    loses the optimization, never correctness). Returns the dropped column
    names so a FILE-level claim source (ORC) can stop re-claiming it for
    every subsequent stripe."""
    import jax

    claimed = [(name, cv) for name, cv in dev_cols.items()
               if cv.vrange is not None and cv.dtype is DataType.INT64]
    if not claimed:
        return []
    reds = [_minmax_valid(cv.data, cv.validity) for _, cv in claimed]
    # ONE stacked transfer: per-scalar device_get blocks once per leaf,
    # which on a tunneled backend costs a ~66 ms fence each
    stacked = _stack_minmax(tuple(reds))
    flat = np.asarray(jax.device_get(stacked))
    vals = [(bool(flat[i, 0]), int(flat[i, 1]), int(flat[i, 2]))
            for i in range(len(reds))]
    dropped: List[str] = []
    for (name, cv), (any_valid, mn, mx) in zip(claimed, vals):
        if not bool(any_valid):
            continue
        lo, hi = cv.vrange
        if int(mn) < lo or int(mx) > hi:
            import logging

            logging.getLogger(__name__).warning(
                "column %r: footer min/max stats (%d, %d) contradict the "
                "decoded data (%d, %d) — corrupt statistics; dropping the "
                "narrowing range", name, lo, hi, int(mn), int(mx))
            cv.vrange = None
            dropped.append(name)
    return dropped


def _pq_stats_vrange(dt: DataType, col_meta) -> Optional[Tuple[int, int]]:
    """(lo, hi) from a parquet column-chunk's footer statistics, for the
    int32-narrowing proof (columnar.batch module docstring). INT64 logical
    columns only — TIMESTAMP never fits int32 and narrower ints gain
    nothing; None when stats are absent/untrusted."""
    from spark_rapids_tpu.columnar.batch import (
        int64_narrowing_enabled,
        quantize_vrange,
    )

    if dt is not DataType.INT64 or not int64_narrowing_enabled():
        return None
    try:
        st = col_meta.statistics
        if st is None or not st.has_min_max:
            return None
        lo, hi = st.min, st.max
        if isinstance(lo, (int, np.integer)) and \
                isinstance(hi, (int, np.integer)):
            return quantize_vrange((int(lo), int(hi)))
    except Exception:
        pass
    return None


def partition_values_of(path: str, roots: List[str]):
    """key=value components of `path` under its root directory, in path
    order (the Hive partition-discovery rule Spark applies)."""
    from urllib.parse import unquote

    for root in roots:
        root = root.rstrip(os.sep)
        if os.path.isdir(root) and path.startswith(root + os.sep):
            rel = os.path.dirname(path[len(root) + 1:])
            out = []
            for comp in rel.split(os.sep):
                if "=" in comp:
                    k, _, v = comp.partition("=")
                    v = unquote(v)
                    out.append((k, None if v == HIVE_NULL else v))
            return tuple(out)
    return ()


def infer_partition_schema(
        pvs: List[Tuple[Tuple[str, Optional[str]], ...]]):
    """Column order + types for discovered partition values (Spark's
    partition-column type inference: int64 -> float64 -> string)."""
    names: List[str] = []
    values: Dict[str, List[Optional[str]]] = {}
    for pv in pvs:
        for k, v in pv:
            if k not in values:
                names.append(k)
                values[k] = []
            values[k].append(v)
    out = []
    for n in names:
        dt = DataType.INT64
        for v in values[n]:
            if v is None:
                continue
            try:
                int(v)
                continue
            except ValueError:
                pass
            try:
                float(v)
                dt = DataType.FLOAT64 if dt is DataType.INT64 else dt
                continue
            except ValueError:
                dt = DataType.STRING
                break
        out.append(AttributeReference(n, dt, True))
    return out


def expand_paths(paths: List[str], suffixes: Tuple[str, ...]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(suffixes) and not f.startswith(("_", ".")):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files under {paths}")
    return out


_SUFFIXES = {
    "parquet": (".parquet", ".parq"),
    "orc": (".orc",),
    "csv": (".csv", ".txt", ".tsv"),
}


def plan_splits(fmt: str, paths: List[str], options: Dict[str, Any],
                conf, files: Optional[List[str]] = None) -> List[FileSplit]:
    """Split input files into read partitions. Parquet splits by row
    groups so each task reads at most maxReadBatchSizeRows rows."""
    from spark_rapids_tpu import conf as C

    files = files or expand_paths(paths, _SUFFIXES.get(fmt, ()))
    opt_t = tuple(sorted(options.items()))
    pvs = {f: partition_values_of(f, paths) for f in files}
    if fmt != "parquet":
        return [FileSplit(f, fmt, None, opt_t, pvs[f]) for f in files]
    import pyarrow.parquet as pq

    max_rows = conf.get(C.MAX_READ_BATCH_SIZE_ROWS)
    splits: List[FileSplit] = []
    for f in files:
        md = pq.ParquetFile(f).metadata
        group: List[int] = []
        rows = 0
        for rg in range(md.num_row_groups):
            n = md.row_group(rg).num_rows
            if group and rows + n > max_rows:
                splits.append(FileSplit(f, fmt, tuple(group), opt_t, pvs[f]))
                group, rows = [], 0
            group.append(rg)
            rows += n
        if group:
            splits.append(FileSplit(f, fmt, tuple(group), opt_t, pvs[f]))
    return splits


def read_split(split: FileSplit,
               attrs: List[AttributeReference]) -> pa.Table:
    names = [a.name for a in attrs]
    if split.fmt == "parquet":
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(split.path)
        groups = list(split.row_groups) if split.row_groups is not None \
            else list(range(pf.metadata.num_row_groups))
        return pf.read_row_groups(groups, columns=names)
    if split.fmt == "orc":
        import pyarrow.orc as po

        return po.ORCFile(split.path).read(columns=names)
    if split.fmt == "csv":
        header = _to_bool(split.opt("header", False))
        sep = split.opt("sep", split.opt("delimiter", ","))
        table = _read_csv_arrow(split.path, names, attrs, sep, header)
        return table.select(names)
    raise ValueError(f"unknown format {split.fmt}")


def _read_csv_arrow(source, file_names, attrs, sep: str, header: bool,
                    include=None):
    """ONE pyarrow CSV option set for the host path and the device path's
    host-rest parse (they must never diverge). `source` is a path or a
    pyarrow buffer reader; `include` restricts converted columns."""
    import pyarrow.csv as pc

    from spark_rapids_tpu.io.arrow_convert import dt_to_arrow_type

    read_opts = pc.ReadOptions(
        column_names=None if header else file_names,
        autogenerate_column_names=False)
    convert = pc.ConvertOptions(
        column_types={a.name: dt_to_arrow_type(a.data_type) for a in attrs},
        include_columns=include,
        strings_can_be_null=True)
    return pc.read_csv(source, read_options=read_opts,
                       parse_options=pc.ParseOptions(delimiter=sep),
                       convert_options=convert)


def _to_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes")


def _with_partition_columns(batch: HostColumnarBatch, attrs,
                            pv: Dict[str, Optional[str]]) -> HostColumnarBatch:
    """Rebuild the batch in `attrs` order, filling partition columns with
    their (parsed) constant directory value."""
    n = batch.num_rows
    by_name = {}
    di = 0
    for a in attrs:
        if a.name in pv:
            continue
        by_name[a.name] = batch.columns[di]
        di += 1
    cols = []
    for a in attrs:
        if a.name not in pv:
            cols.append(by_name[a.name])
            continue
        raw = pv[a.name]
        if raw is None:
            validity = np.zeros(n, dtype=bool)
            if a.data_type is DataType.STRING:
                data = np.full(n, "", dtype=object)
            else:
                data = np.zeros(n, dtype=a.data_type.to_np())
        else:
            validity = np.ones(n, dtype=bool)
            if a.data_type is DataType.STRING:
                data = np.full(n, raw, dtype=object)
            elif a.data_type is DataType.FLOAT64:
                data = np.full(n, float(raw), dtype=np.float64)
            else:
                data = np.full(n, int(raw), dtype=a.data_type.to_np())
        cols.append(HostColumnVector(a.data_type, data, validity))
    return HostColumnarBatch(cols, n)


class _FileScanBase(PhysicalExec):
    def __init__(self, attrs: List[AttributeReference],
                 splits: List[FileSplit], fmt: str):
        super().__init__()
        self.attrs = attrs
        self.splits = splits
        self.fmt = fmt

    @property
    def output(self) -> List[AttributeReference]:
        return self.attrs

    @property
    def coalesce_after(self) -> bool:
        # scans emit per-row-group/per-chunk batches; coalescing them to the
        # target batch size is the reference's signature plan shape
        # (GpuScans set coalesceAfter, GpuCoalesceBatches sits above scans)
        return True

    def with_children(self, new_children):
        assert not new_children
        return self

    def node_name(self):
        return f"{type(self).__name__}({self.fmt}, {len(self.splits)} splits)"

    def _read_host_iter(self, pidx: int, conf):
        """Generator form of the host decode: the Arrow read runs on first
        pull, so a prefetch wrapper (io/prefetch.py) moves the WHOLE decode
        onto its worker thread — batch k+1 of the query decodes while
        batch k computes downstream."""
        from spark_rapids_tpu import conf as C

        split = self.splits[pidx]
        pv = dict(split.partition_values)
        data_attrs = [a for a in self.attrs if a.name not in pv]
        table = read_split(split, data_attrs)
        batch = arrow_to_host_batch(table, data_attrs)
        if pv:
            # append partition-value constant columns (reference:
            # ColumnarPartitionReaderWithPartitionValues)
            batch = _with_partition_columns(batch, self.attrs, pv)
        max_rows = conf.get(C.MAX_READ_BATCH_SIZE_ROWS)
        if batch.num_rows <= max_rows:
            yield batch
            return
        for i in range(0, batch.num_rows, max_rows):
            yield batch.slice(i, max_rows)

    def _host_batches_prefetched(self, pidx: int, conf):
        """Host decode iterator with the configured double-buffering depth
        (rapids.tpu.io.prefetchBatches; per-read option overrides)."""
        from spark_rapids_tpu.io.prefetch import maybe_prefetch, prefetch_depth

        return maybe_prefetch(
            self._read_host_iter(pidx, conf),
            prefetch_depth(conf, self.splits[pidx]))


class CpuFileScanExec(_FileScanBase, CpuExec):
    placement = "cpu"

    def execute(self, ctx: ExecContext) -> PartitionedBatches:
        def factory(pidx: int):
            return count_output(
                self.metrics,
                self._host_batches_prefetched(pidx, ctx.conf))

        return PartitionedBatches(len(self.splits), factory)


class TpuFileScanExec(_FileScanBase, TpuExec):
    """Parquet columns that qualify decode ON DEVICE from raw chunk bytes
    (io/parquet_device.py — the reference's accelerator-side decode,
    GpuParquetScan.scala:536-556); everything else host-decodes via Arrow
    and uploads. The admission semaphore is acquired exactly where the
    reference acquires it: before bytes go on the device
    (GpuParquetScan.scala:554)."""

    placement = "tpu"

    def execute(self, ctx: ExecContext) -> PartitionedBatches:
        from spark_rapids_tpu import conf as C

        device_decode = self.fmt == "parquet" and \
            ctx.conf.get(C.PARQUET_DEVICE_DECODE)
        device_csv = self.fmt == "csv" and ctx.conf.get(C.CSV_DEVICE_PARSE)
        device_orc = self.fmt == "orc" and ctx.conf.get(C.ORC_DEVICE_DECODE)

        def factory(pidx: int):
            from spark_rapids_tpu.engine.retry import with_retry

            def gen():
                # device decodes are pure over (split bytes, conf): a
                # retryable OOM/transient error re-reads and re-decodes the
                # split after the spill (with_retry); exhaustion propagates
                # for task retry / query-level CPU fallback
                if device_decode:
                    batches = with_retry(
                        lambda: self._read_device(self.splits[pidx],
                                                  ctx.conf), site="scan")
                    if batches is not None:
                        yield from batches
                        return
                if device_csv:
                    batches = with_retry(
                        lambda: self._read_device_csv(self.splits[pidx],
                                                      ctx.conf), site="scan")
                    if batches is not None:
                        yield from batches
                        return
                if device_orc:
                    # per-stripe generator: a retry wrapper around next()
                    # could silently truncate a closed generator, so device
                    # ORC errors propagate to the task-level retry instead
                    batches = self._read_device_orc(self.splits[pidx],
                                                    ctx.conf)
                    if batches is not None:
                        yield from batches
                        return
                # host path: decode double-buffers on the prefetch worker;
                # the upload ISSUES here (asynchronously — jax returns an
                # unblocked device future) under this task's admission
                # permit, so batch k+1's decode and upload overlap batch
                # k's downstream compute
                for hb in self._host_batches_prefetched(pidx, ctx.conf):
                    TpuSemaphore.get().acquire_if_necessary(current_task_id())
                    yield with_retry(lambda: hb.to_device(), site="scan")

            return count_output(self.metrics, gen())

        return PartitionedBatches(len(self.splits), factory)

    def _read_device_csv(self, split: FileSplit, conf):
        """Device CSV parse for one split; None -> structure/columns not
        eligible (caller uses the host Arrow path). Mirrors _read_device:
        integral columns parse on device from the raw bytes, everything
        else host-parses and uploads."""
        from spark_rapids_tpu.columnar.batch import (
            ColumnVector,
            bucket_capacity,
        )
        from spark_rapids_tpu.io import csv_device as CD
        from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch

        pv = dict(split.partition_values)
        data_attrs = [a for a in self.attrs if a.name not in pv]
        if not any(CD.device_parseable(a.data_type) for a in data_attrs):
            return None
        header = _to_bool(split.opt("header", False))
        sep = split.opt("sep", split.opt("delimiter", ","))
        if not isinstance(sep, str) or len(sep) != 1:
            return None
        from spark_rapids_tpu import conf as C

        if os.path.getsize(split.path) > conf.get(C.CSV_DEVICE_MAX_SPLIT_BYTES):
            # the whole-file boundary plan costs rows*cols int32 tables in
            # host RAM; past this size the streaming Arrow path is cheaper
            return None
        with open(split.path, "rb") as f:
            data = f.read()
        if not data:
            return None
        first_nl = data.find(b"\n")
        first_line = data[:first_nl if first_nl >= 0 else len(data)]
        ncols = first_line.count(sep.encode()) + 1
        if not header and ncols != len(data_attrs):
            return None
        table = CD.plan_fields(data, ncols, header, sep)
        if table is None:
            return None
        eligible = CD.eligible_attrs(data_attrs, table.header_names,
                                     [a.name for a in data_attrs])
        if not eligible:
            return None
        has_dev_strings = any(
            a.data_type is DataType.STRING and a.name in eligible
            for a in data_attrs)
        if has_dev_strings:
            # the host oracle validates UTF-8 on string conversion; the
            # device path carries raw bytes, so gate up front — on invalid
            # input the host path raises the error both engines must raise
            try:
                data.decode("utf-8")
            except UnicodeDecodeError:
                return None
        rows = table.num_rows
        cap = bucket_capacity(max(rows, 1))
        TpuSemaphore.get().acquire_if_necessary(current_task_id())
        import jax

        dev_cols = {}
        malformed_flags = []
        for a in data_attrs:
            if a.name not in eligible:
                continue
            if a.data_type is DataType.STRING:
                dev_cols[a.name] = CD.decode_string_column(
                    table, eligible[a.name], cap)
                continue
            d, v, bad = CD.decode_column(table, eligible[a.name],
                                         a.data_type, cap)
            malformed_flags.append(bad)
            dev_cols[a.name] = ColumnVector(a.data_type, d, v)
        if malformed_flags and any(
                bool(x) for x in jax.device_get(malformed_flags)):
            # malformed field somewhere: ONE batched sync, then the host
            # parser raises the same error both engines would
            return None
        rest = [a for a in data_attrs if a.name not in dev_cols]
        hb = None
        if rest:
            # host-parse ONLY the non-device columns, from the bytes already
            # in memory — never a second disk read, never re-converting the
            # columns the device just parsed
            import pyarrow as pa

            all_names = table.header_names if header \
                else [a.name for a in data_attrs]
            tbl = _read_csv_arrow(pa.BufferReader(data), all_names, rest,
                                  sep, header,
                                  include=[a.name for a in rest])
            hb = arrow_to_host_batch(tbl, rest)
            if hb.num_rows != rows:
                return None  # host parser disagrees: fall back
        return self._assemble_device_batch(dev_cols, hb, rest, pv, rows,
                                           conf)

    def _read_device_orc(self, split: FileSplit, conf):
        """Device ORC decode for one split; None -> not eligible (caller
        uses the host Arrow path). Two phases: (1) HOST-ONLY planning —
        protobuf walk + run tables for every stripe/column, so any
        unsupported shape falls back before a single device byte moves;
        (2) a generator that, per stripe, acquires the admission semaphore,
        uploads JUST that stripe's region, expands on device, and yields —
        peak HBM is one stripe, not the file."""
        from spark_rapids_tpu.io import orc_device as OD

        pv = dict(split.partition_values)
        data_attrs = [a for a in self.attrs if a.name not in pv]
        try:
            with open(split.path, "rb") as f:
                # tail-first: reject unsupported codecs from the PostScript
                # alone, before a full-file read (zlib/snappy streams
                # decompress on the host into the device expansion)
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 4096))
                if OD.tail_compression(f.read()) not in \
                        OD.SUPPORTED_COMPRESSION:
                    return None
                f.seek(0)
                raw = f.read()
            meta = OD.parse_file_meta(raw)
        except (OD._Unsupported, OSError):
            return None
        name_to_cid = {n: i for i, n in enumerate(meta.names) if n}
        eligible = [a for a in data_attrs
                    if a.name in name_to_cid and
                    OD.column_eligible(meta, name_to_cid[a.name],
                                       a.data_type)]
        if not eligible:
            return None
        rest = [a for a in data_attrs if a not in eligible]
        # phase 1: host-only plans for every stripe x eligible column
        stripe_plans = []
        try:
            for si in meta.stripes:
                if meta.compression != 0:
                    region = raw[si.offset:
                                 si.offset + si.index_length +
                                 si.data_length + si.footer_length]
                    norm, streams, encs, tz = OD.normalize_stripe(
                        region, si, meta.compression,
                        {name_to_cid[a.name] for a in eligible})
                    plans = {
                        a.name: OD.plan_column(norm, streams, encs,
                                               name_to_cid[a.name],
                                               si.num_rows, 0,
                                               dtype=a.data_type,
                                               timezone=tz)
                        for a in eligible}
                else:
                    streams, encs, tz = OD.parse_stripe_footer(raw, si)
                    plans = {
                        a.name: OD.plan_column(raw, streams, encs,
                                               name_to_cid[a.name],
                                               si.num_rows, si.offset,
                                               dtype=a.data_type,
                                               timezone=tz)
                        for a in eligible}
                stripe_plans.append(plans)
        except Exception:
            return None  # unsupported shape anywhere: whole-split fallback

        # the generator re-reads each stripe region from disk on demand —
        # `raw` must NOT outlive phase 1, so peak host memory during the
        # scan is one stripe, not the file
        del raw
        return self._orc_stripe_batches(split, meta, stripe_plans,
                                        eligible, rest, pv, conf,
                                        {name_to_cid[a.name]
                                         for a in eligible})

    def _orc_stripe_batches(self, split, meta, stripe_plans, eligible,
                            rest, pv, conf, eligible_cids=None):
        """Phase 2 generator: per-stripe read + upload + expand + yield."""
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.batch import (
            ColumnVector,
            bucket_capacity,
        )
        from spark_rapids_tpu.io import orc_device as OD

        orc_file = None
        for sidx, si in enumerate(meta.stripes):
            rows = si.num_rows
            cap = bucket_capacity(max(rows, 1))
            TpuSemaphore.get().acquire_if_necessary(current_task_id())
            with open(split.path, "rb") as f:
                f.seek(si.offset)
                region = f.read(si.index_length + si.data_length +
                                si.footer_length)
            if meta.compression != 0:
                # deterministic re-normalization over the SAME column set:
                # plan offsets index the same decompressed image (peak host
                # memory stays one stripe; decompression is host
                # control-plane work)
                region, _streams, _encs, _tz = OD.normalize_stripe(
                    region, si, meta.compression, eligible_cids)
            stripe_dev = jnp.asarray(np.frombuffer(region, dtype=np.uint8))
            from spark_rapids_tpu import conf as C3
            from spark_rapids_tpu.columnar import encoded as ENC

            enc_ok = conf.get(C3.ENCODED_ENABLED)
            enc_frac = conf.get(C3.ENCODED_MAX_DICT_FRACTION)
            dev_cols = {}
            for a in eligible:
                if a.data_type is DataType.STRING:
                    plan = stripe_plans[sidx][a.name]
                    if enc_ok and plan.dict_len_rt is not None and \
                            ENC.scan_encoded_ok(plan.dict_size, rows,
                                                enc_frac):
                        # DICTIONARY_V2 stays ENCODED: codes off the
                        # index stream, dictionary bytes interned from
                        # the host stripe image — ORC joins the
                        # code-space pipeline on the same eligibility
                        # as parquet (columnar/encoded.py)
                        codes, v, lens_np = OD.expand_string_codes(
                            stripe_dev, plan, rows, cap)
                        offs_np = np.zeros(len(lens_np) + 1,
                                           dtype=np.int32)
                        np.cumsum(lens_np, out=offs_np[1:])
                        db = np.frombuffer(
                            region, dtype=np.uint8,
                            count=int(offs_np[-1]),
                            offset=plan.data_start).copy()
                        dct = ENC.DeviceDictionary.from_byte_table(
                            db, offs_np)
                        cv = ENC.DictionaryColumn(a.data_type, codes, v,
                                                  dct)
                        ENC.record_scan_emission(cv, rows)
                        dev_cols[a.name] = cv
                        continue
                    d, v, offs = OD.expand_string_column(
                        stripe_dev, plan, rows, cap)
                    dev_cols[a.name] = ColumnVector(a.data_type, d, v,
                                                    offs)
                elif a.data_type in (DataType.FLOAT32, DataType.FLOAT64):
                    d, v = OD.expand_float_column(
                        stripe_dev, stripe_plans[sidx][a.name],
                        a.data_type, rows, cap)
                    dev_cols[a.name] = ColumnVector(a.data_type, d, v)
                elif a.data_type is DataType.BOOL:
                    d, v = OD.expand_bool_column(
                        stripe_dev, stripe_plans[sidx][a.name], rows, cap)
                    dev_cols[a.name] = ColumnVector(a.data_type, d, v)
                elif a.data_type is DataType.TIMESTAMP:
                    d, v = OD.expand_timestamp_column(
                        stripe_dev, stripe_plans[sidx][a.name], rows, cap)
                    dev_cols[a.name] = ColumnVector(a.data_type, d, v)
                else:
                    d, v = OD.expand_column(stripe_dev,
                                            stripe_plans[sidx][a.name],
                                            a.data_type, rows, cap)
                    dev_cols[a.name] = ColumnVector(
                        a.data_type, d, v,
                        vrange=_orc_stats_vrange(a, meta))
            # ORC stats are FILE-level: a claim one stripe disproves must
            # not be re-claimed (re-reduced, re-warned) by later stripes
            for name in verify_footer_vranges(dev_cols):
                cid = meta.names.index(name)
                if 0 <= cid < len(meta.col_stats):
                    meta.col_stats[cid] = None
            hb = None
            if rest:
                import pyarrow.orc as po

                if orc_file is None:
                    orc_file = po.ORCFile(split.path)
                rb = orc_file.read_stripe(sidx,
                                          columns=[a.name for a in rest])
                hb = arrow_to_host_batch(pa.Table.from_batches([rb]), rest)
                if hb.num_rows != rows:
                    raise IOError(
                        f"ORC stripe {sidx} row-count mismatch: device "
                        f"plan {rows} vs host {hb.num_rows}")
            yield from self._assemble_device_batch(dev_cols, hb, rest, pv,
                                                   rows, conf)

    def _assemble_device_batch(self, dev_cols, hb, rest, pv, rows, conf):
        """Combine device-decoded columns with a host-decoded partial batch
        (+ partition-value columns) into output batches, sliced to
        MAX_READ_BATCH_SIZE_ROWS. Shared by the parquet and CSV device read
        paths — their mixed-batch assembly must never diverge."""
        from spark_rapids_tpu import conf as C2
        from spark_rapids_tpu.columnar.batch import (
            ColumnarBatch,
            slice_batch_host,
        )

        host_part = None
        host_names: List[str] = []
        if hb is None and pv:
            hb = HostColumnarBatch([], rows)
        if hb is not None:
            if pv:
                hb = _with_partition_columns(
                    hb, rest + [a for a in self.attrs if a.name in pv], pv)
            host_part = hb.to_device()
            host_names = [a.name for a in rest] + \
                [a.name for a in self.attrs if a.name in pv]
        cols = []
        for a in self.attrs:
            if a.name in dev_cols:
                cols.append(dev_cols[a.name])
            else:
                cols.append(host_part.columns[host_names.index(a.name)])
        # decode-kernel outputs + a fresh upload: consume-once by
        # construction, like the host path's to_device batches — keeps
        # the analyzer's scan-input donation credit sound
        batch = ColumnarBatch(cols, rows, owned=True)
        max_rows = conf.get(C2.MAX_READ_BATCH_SIZE_ROWS)
        if rows <= max_rows:
            return [batch]
        return [slice_batch_host(batch, i, max_rows)
                for i in range(0, rows, max_rows)]

    def encoded_plan(self, conf) -> Dict[str, str]:
        """Plan-time mirror of the runtime encoded-scan decision
        (columnar/encoded.py): column name -> 'certain' (every row group
        of every split is a dictionary-only chunk that clears the
        ndv/rows heuristic — the decode WILL emit codes) or 'possible'
        (a dictionary page exists somewhere but dict-only-ness or the
        heuristic cannot be proven from footers alone). The resource
        analyzer reduces its byte model only for 'certain' columns (the
        pessimistic ceiling must stay sound) and widens its savings
        interval over 'possible' ones (containment against the measured
        metric). Cached per (enabled, fraction) on the exec."""
        from spark_rapids_tpu import conf as C3
        from spark_rapids_tpu.columnar import encoded as ENC
        from spark_rapids_tpu.io import parquet_device as PD

        enabled = conf.get(C3.ENCODED_ENABLED) and (
            (self.fmt == "parquet"
             and conf.get(C3.PARQUET_DEVICE_DECODE))
            or (self.fmt == "orc" and conf.get(C3.ORC_DEVICE_DECODE)))
        frac = conf.get(C3.ENCODED_MAX_DICT_FRACTION)
        fixed_conf = conf.get(C3.ENCODED_FIXED_DICTIONARIES)
        cached = getattr(self, "_encoded_plan_cache", None)
        if cached is not None and cached[0] == (enabled, frac,
                                                fixed_conf):
            return cached[1]
        out: Dict[str, str] = {}
        if enabled and self.fmt == "orc":
            # ORC: a stripe's DICTIONARY_V2 choice + dictionarySize live
            # in the stripe FOOTER — 'possible' when any stripe might
            # encode (the savings interval must cover it); 'certain' is
            # NOT claimed (the byte model's pessimistic ceiling stays on
            # the decoded estimate; runtime decides per stripe).
            # METADATA cost only: file meta from the tail, then each
            # stripe's footer bytes read + parsed ONCE for all columns —
            # never the data streams.
            try:
                from spark_rapids_tpu.io import orc_device as OD

                for split in self.splits:
                    size = os.path.getsize(split.path)
                    with open(split.path, "rb") as f:
                        f.seek(max(0, size - (1 << 20)))
                        tail = f.read()
                        try:
                            meta = OD.parse_file_meta(tail)
                        except Exception:
                            f.seek(0)
                            meta = OD.parse_file_meta(f.read())
                        name_to_cid = {n: i for i, n in
                                       enumerate(meta.names)}
                        want = {name_to_cid[a.name]: a.name
                                for a in self.attrs
                                if a.data_type is DataType.STRING
                                and a.name not in out
                                and a.name in name_to_cid}
                        for si in meta.stripes:
                            if not want:
                                break
                            fstart = si.offset + si.index_length + \
                                si.data_length
                            f.seek(fstart)
                            fbytes = f.read(si.footer_length)
                            if meta.compression != 0:
                                fbuf = OD.decompress_blocks(
                                    fbytes, 0, si.footer_length,
                                    meta.compression)
                            else:
                                fbuf = fbytes
                            _s, encs, _tz = OD._walk_stripe_footer(
                                fbuf, 0, len(fbuf), 0)
                            for cid in list(want):
                                enc, dict_size = encs.get(cid, (-1, 0))
                                if enc == OD.E_DICT_V2 and \
                                        ENC.scan_encoded_ok(
                                            dict_size, si.num_rows,
                                            frac):
                                    out[want.pop(cid)] = "possible"
            except Exception:
                out = {}
            self._encoded_plan_cache = ((enabled, frac, fixed_conf), out)
            return out
        if enabled:
            import pyarrow.parquet as pq

            fixed_ok = fixed_conf
            str_attrs = [a for a in self.attrs
                         if a.data_type is DataType.STRING
                         or (fixed_ok and a.data_type in (
                             DataType.INT64, DataType.DATE,
                             DataType.TIMESTAMP))]
            # per column: 'certain' only when EVERY row group of every
            # split is a provably dict-only chunk clearing the heuristic;
            # 'possible' when ANY group might encode (the savings
            # interval must cover it); absent otherwise
            all_certain: Dict[str, bool] = {}
            any_possible: Dict[str, bool] = {}
            try:
                for split in self.splits:
                    md = pq.ParquetFile(split.path).metadata
                    schema_index = {
                        md.row_group(0).column(ci).path_in_schema: ci
                        for ci in range(md.num_columns)}
                    groups = list(split.row_groups) \
                        if split.row_groups is not None \
                        else list(range(md.num_row_groups))
                    for a in str_attrs:
                        ci = schema_index.get(a.name)
                        all_certain.setdefault(a.name, True)
                        if ci is None:
                            all_certain[a.name] = False
                            continue
                        for rg in groups:
                            col = md.row_group(rg).column(ci)
                            rows = md.row_group(rg).num_rows
                            ndv = PD.chunk_dict_ndv(split.path, col)
                            ok = (PD.column_eligible(col, a.data_type)
                                  and ndv is not None
                                  and ENC.scan_encoded_ok(ndv, rows, frac))
                            if not ok:
                                all_certain[a.name] = False
                                continue
                            any_possible[a.name] = True
                            # 'certain' needs a page-header walk: footer
                            # encodings cannot distinguish a pure-dict
                            # chunk from a mid-chunk PLAIN fallback
                            if PD.chunk_dict_only(split.path, col) \
                                    is not True:
                                all_certain[a.name] = False
                for name in any_possible:
                    out[name] = "certain" if all_certain.get(name) \
                        else "possible"
            except Exception:
                out = {}
        self._encoded_plan_cache = ((enabled, frac, fixed_conf), out)
        return out

    def _read_device(self, split: FileSplit, conf):
        """Device decode for one split; None -> no column qualified (caller
        uses the host path). Mixed batches combine device-decoded columns
        with host-decoded/partition-value columns at the same capacity."""
        import pyarrow.parquet as pq

        from spark_rapids_tpu.columnar.batch import bucket_capacity
        from spark_rapids_tpu.io import parquet_device as PD
        from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch

        from spark_rapids_tpu import conf as C3
        from spark_rapids_tpu.columnar import encoded as ENC

        encoded_ok = conf.get(C3.ENCODED_ENABLED)
        fixed_ok = encoded_ok and conf.get(C3.ENCODED_FIXED_DICTIONARIES)
        max_frac = conf.get(C3.ENCODED_MAX_DICT_FRACTION)
        pf = pq.ParquetFile(split.path)
        md = pf.metadata
        pv = dict(split.partition_values)
        schema_index = {md.row_group(0).column(ci).path_in_schema: ci
                        for ci in range(md.num_columns)}
        # required columns carry NO definition levels in v1 data pages —
        # max_def must match or the value stream is misparsed
        max_def = {pf.schema.column(ci).name:
                   pf.schema.column(ci).max_definition_level
                   for ci in range(len(pf.schema.names))}
        # FLBA byte length per column (decimals; 0 for other physicals)
        flba_len = {pf.schema.column(ci).name:
                    (getattr(pf.schema.column(ci), "length", 0) or 0)
                    for ci in range(len(pf.schema.names))}
        data_attrs = [a for a in self.attrs if a.name not in pv]
        eligible = []
        for a in data_attrs:
            ci = schema_index.get(a.name)
            if ci is not None and PD.column_eligible(
                    md.row_group(0).column(ci), a.data_type):
                eligible.append(a)
        if not eligible:
            return None
        groups = list(split.row_groups) if split.row_groups is not None \
            else list(range(md.num_row_groups))
        rest = [a for a in data_attrs if a not in eligible]
        out = []
        for rg in groups:
            rows = md.row_group(rg).num_rows
            cap = bucket_capacity(max(rows, 1))
            TpuSemaphore.get().acquire_if_necessary(current_task_id())
            dev_cols = {}
            for a in eligible:
                col = md.row_group(rg).column(schema_index[a.name])
                chunk = PD.read_chunk_bytes(split.path, col)
                try:
                    dev_cols[a.name] = PD.decode_chunk_device(
                        chunk, a.data_type, rows,
                        max_def=max_def.get(a.name, 1), cap=cap,
                        codec=col.compression,
                        flba_len=flba_len.get(a.name, 0),
                        encoded_ok=(
                            (encoded_ok
                             and a.data_type is DataType.STRING)
                            or (fixed_ok and a.data_type in (
                                DataType.INT64, DataType.DATE,
                                DataType.TIMESTAMP))),
                        max_dict_fraction=max_frac)
                except Exception:
                    return None  # unexpected page shape: whole-split fallback
                if ENC.is_encoded(dev_cols[a.name]):
                    ENC.record_scan_emission(dev_cols[a.name], rows)
                # footer statistics -> value range: device-decoded columns
                # never pass through a host array, so the upload-time min/max
                # pass (columnar.batch.host_value_range) can't see them; the
                # writer's chunk stats carry the same proof for free
                dev_cols[a.name].vrange = _pq_stats_vrange(a.data_type, col)
            verify_footer_vranges(dev_cols)
            hb = None
            if rest or pv:
                sub = FileSplit(split.path, "parquet", (rg,), split.options,
                                split.partition_values)
                table = read_split(sub, rest)
                hb = arrow_to_host_batch(table, rest)
            out.extend(self._assemble_device_batch(dev_cols, hb, rest, pv,
                                                   rows, conf))
        return out
