"""File scan execs (reference: GpuParquetScan.scala, GpuOrcScan.scala,
GpuBatchScanExec.scala CSV).

Reference parity:
- read-partition planning by row-group/row-count caps
  (populateCurrentBlockChunk, GpuParquetScan.scala:571-605;
  maxReadBatchSizeRows/Bytes, RapidsConf.scala:315-322) -> `plan_splits`.
- host-side read + device upload with task admission
  (semaphore acquire before decode/upload, GpuParquetScan.scala:300,554) ->
  `TpuFileScanExec` host-decodes via Arrow C++ then does the packed
  single-copy upload under the TpuSemaphore.
- per-format enable confs (RapidsConf.scala:433-469) -> tagged in
  plan/overrides.py.

Phase 1 decodes on the host with Arrow C++ (the correctness oracle the
SURVEY.md build plan keeps); phase 2+ moves Parquet dictionary/RLE decode
into Pallas kernels fed by raw column chunks.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.batch import HostColumnarBatch
from spark_rapids_tpu.exec.base import (
    CpuExec,
    ExecContext,
    PartitionedBatches,
    PhysicalExec,
    TpuExec,
    count_output,
)
from spark_rapids_tpu.exec.transitions import current_task_id
from spark_rapids_tpu.io.arrow_convert import arrow_to_host_batch
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.ops.base import AttributeReference
from spark_rapids_tpu.utils import metrics as M


@dataclass(frozen=True)
class FileSplit:
    """One read task: a file plus (for parquet) the row groups to read."""

    path: str
    fmt: str
    row_groups: Optional[Tuple[int, ...]] = None
    options: Tuple[Tuple[str, Any], ...] = ()

    def opt(self, key: str, default=None):
        return dict(self.options).get(key, default)


def expand_paths(paths: List[str], suffixes: Tuple[str, ...]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(suffixes) and not f.startswith(("_", ".")):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files under {paths}")
    return out


_SUFFIXES = {
    "parquet": (".parquet", ".parq"),
    "orc": (".orc",),
    "csv": (".csv", ".txt", ".tsv"),
}


def plan_splits(fmt: str, paths: List[str], options: Dict[str, Any],
                conf) -> List[FileSplit]:
    """Split input files into read partitions. Parquet splits by row
    groups so each task reads at most maxReadBatchSizeRows rows."""
    from spark_rapids_tpu import conf as C

    files = expand_paths(paths, _SUFFIXES.get(fmt, ()))
    opt_t = tuple(sorted(options.items()))
    if fmt != "parquet":
        return [FileSplit(f, fmt, None, opt_t) for f in files]
    import pyarrow.parquet as pq

    max_rows = conf.get(C.MAX_READ_BATCH_SIZE_ROWS)
    splits: List[FileSplit] = []
    for f in files:
        md = pq.ParquetFile(f).metadata
        group: List[int] = []
        rows = 0
        for rg in range(md.num_row_groups):
            n = md.row_group(rg).num_rows
            if group and rows + n > max_rows:
                splits.append(FileSplit(f, fmt, tuple(group), opt_t))
                group, rows = [], 0
            group.append(rg)
            rows += n
        if group:
            splits.append(FileSplit(f, fmt, tuple(group), opt_t))
    return splits


def read_split(split: FileSplit,
               attrs: List[AttributeReference]) -> pa.Table:
    names = [a.name for a in attrs]
    if split.fmt == "parquet":
        import pyarrow.parquet as pq

        pf = pq.ParquetFile(split.path)
        groups = list(split.row_groups) if split.row_groups is not None \
            else list(range(pf.metadata.num_row_groups))
        return pf.read_row_groups(groups, columns=names)
    if split.fmt == "orc":
        import pyarrow.orc as po

        return po.ORCFile(split.path).read(columns=names)
    if split.fmt == "csv":
        import pyarrow.csv as pc

        header = _to_bool(split.opt("header", False))
        sep = split.opt("sep", split.opt("delimiter", ","))
        read_opts = pc.ReadOptions(
            column_names=None if header else names, autogenerate_column_names=False)
        parse_opts = pc.ParseOptions(delimiter=sep)
        from spark_rapids_tpu.io.arrow_convert import dt_to_arrow_type

        convert = pc.ConvertOptions(
            column_types={a.name: dt_to_arrow_type(a.data_type)
                          for a in attrs},
            strings_can_be_null=True)
        table = pc.read_csv(split.path, read_options=read_opts,
                            parse_options=parse_opts,
                            convert_options=convert)
        return table.select(names)
    raise ValueError(f"unknown format {split.fmt}")


def _to_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes")


class _FileScanBase(PhysicalExec):
    def __init__(self, attrs: List[AttributeReference],
                 splits: List[FileSplit], fmt: str):
        super().__init__()
        self.attrs = attrs
        self.splits = splits
        self.fmt = fmt

    @property
    def output(self) -> List[AttributeReference]:
        return self.attrs

    def with_children(self, new_children):
        assert not new_children
        return self

    def node_name(self):
        return f"{type(self).__name__}({self.fmt}, {len(self.splits)} splits)"

    def _read_host(self, pidx: int, conf) -> List[HostColumnarBatch]:
        from spark_rapids_tpu import conf as C

        table = read_split(self.splits[pidx], self.attrs)
        batch = arrow_to_host_batch(table, self.attrs)
        max_rows = conf.get(C.MAX_READ_BATCH_SIZE_ROWS)
        if batch.num_rows <= max_rows:
            return [batch]
        return [batch.slice(i, max_rows)
                for i in range(0, batch.num_rows, max_rows)]


class CpuFileScanExec(_FileScanBase, CpuExec):
    placement = "cpu"

    def execute(self, ctx: ExecContext) -> PartitionedBatches:
        def factory(pidx: int):
            return count_output(self.metrics,
                                iter(self._read_host(pidx, ctx.conf)))

        return PartitionedBatches(len(self.splits), factory)


class TpuFileScanExec(_FileScanBase, TpuExec):
    """Host decode + packed upload per split, gated by the admission
    semaphore exactly where the reference acquires it (before putting bytes
    on the device, GpuParquetScan.scala:554)."""

    placement = "tpu"

    def execute(self, ctx: ExecContext) -> PartitionedBatches:
        def factory(pidx: int):
            def gen():
                for hb in self._read_host(pidx, ctx.conf):
                    TpuSemaphore.get().acquire_if_necessary(current_task_id())
                    yield hb.to_device()

            return count_output(self.metrics, gen())

        return PartitionedBatches(len(self.splits), factory)
