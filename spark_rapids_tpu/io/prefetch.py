"""Bounded background prefetch for scan decode (double buffering).

The issue-ahead executor (docs/async-execution.md) removes the host's
mid-query waits on the DEVICE; this module removes the symmetric stall on
the HOST side of a scan: with a prefetch depth of k, a daemon reader
thread decodes batch n+1..n+k while the consumer computes on batch n —
Arrow/pyarrow decode releases the GIL for its I/O and parse work, so the
overlap is real parallelism, not just interleaving. The consumer then
uploads on ITS OWN thread (admission-semaphore acquisition is per task
id, and JAX uploads are asynchronous anyway, so the upload also overlaps
compute without the prefetcher touching device state).

Depth is `rapids.tpu.io.prefetchBatches` (0 = off, decode inline), with a
per-read override via `spark.read.option("prefetchBatches", k)`.

Contract:
- item order is preserved exactly (FIFO);
- an exception in the source iterator propagates to the consumer at the
  position where the item would have appeared (fault-injection and IO
  errors keep their per-batch attribution);
- `close()` (also called by __del__ and at exhaustion) stops the worker
  promptly AND joins the reader thread with a bounded timeout — a
  consumer that abandons the iterator (LIMIT early-exit, task retry,
  cancellation) leaves zero live threads behind (pinned by test);
- cancellation-aware (engine/cancel.py): the consumer's queue waits and
  the worker's puts both watch the constructing query's CancelToken, so
  a cancelled query's reader dies at the next poll instead of decoding
  an unbounded stream for nobody.
"""

from __future__ import annotations

import contextvars
import queue
import threading
from typing import Iterator, Optional, TypeVar

T = TypeVar("T")

_END = object()

# thread-name prefix every reader carries: the live-thread census
# (live_reader_count, the post-cancel reclamation invariant) keys on it
_THREAD_PREFIX = "srt-prefetch:"

# bounded waits: the consumer's queue-poll cadence (each wakeup re-checks
# closed + cancel) and the close()-time thread join bound
_POLL_S = 0.1
_JOIN_S = 5.0


def live_reader_count() -> int:
    """Live prefetch reader threads in the process (the reclamation
    invariant surface: after a cancellation — or any abandoned scan —
    this must return to zero, engine/cancel.reclamation_report)."""
    return sum(1 for t in threading.enumerate()
               if t.name.startswith(_THREAD_PREFIX) and t.is_alive())


def _prefetch_worker(source, q: "queue.Queue", closed: threading.Event,
                     token) -> None:
    """Worker body — a free function on purpose: a bound-method target
    would give the thread a strong reference to the iterator, so an
    abandoned PrefetchIterator could never be garbage-collected and its
    worker (plus the staged batches) would leak for the session's
    lifetime. Every put (items AND the END/error sentinel) retries with a
    timeout so a consumer that stopped draining can never wedge the
    worker — close() (or GC -> __del__ -> close()) sets `closed`, a
    query cancel fires `token`, and the worker exits at the next poll."""
    def dead() -> bool:
        return closed.is_set() or \
            (token is not None and token.cancelled)

    def put(payload) -> bool:
        while not dead():
            try:
                q.put(payload, timeout=_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    try:
        for item in source:
            if not put(("item", item)):
                return
            if dead():
                return
        put((None, _END))
    except BaseException as e:  # noqa: BLE001 - relayed to consumer
        put(("error", e))


class PrefetchIterator:
    """Iterate `source` with up to `depth` items staged ahead by a daemon
    worker thread (depth >= 1; use maybe_prefetch for the 0 = inline
    gate)."""

    def __init__(self, source: Iterator[T], depth: int,
                 name: str = "scan-prefetch"):
        self._depth = max(1, int(depth))
        # exactly `depth` staged items; the END/error sentinel needs no
        # reserved slot because every put retries with a timeout. Total
        # decoded batches live per consumer: depth (queue) + 1 in the
        # worker's hand + the consumer's current one — the (2 + depth)
        # the resource analyzer charges scan leaves
        self._queue: "queue.Queue" = queue.Queue(self._depth)
        self._closed = threading.Event()
        # queue-occupancy telemetry (docs/observability.md): the staged
        # depth observed at each consumer arrival — high-water ~= depth
        # means the reader keeps ahead (prefetch is winning); ~= 0 means
        # decode is the bottleneck. Reported as one completed span on the
        # constructing query's tracer at close(); tracing off = all None
        # checks, no clock reads.
        from spark_rapids_tpu.obs.trace import (
            current_span,
            current_tracer,
            wall_ns,
        )

        self._name = name
        self._tracer = current_tracer()
        # parent captured NOW: close() may run late (GC __del__) on a
        # thread whose current span belongs to a different query
        self._parent_span = current_span() if self._tracer is not None \
            else None
        self._start_ns = wall_ns() if self._tracer is not None else 0
        self._occ_high = 0
        self._items = 0
        self._reported = False
        # the constructing query's CancelToken (engine/cancel.py): both
        # sides of the queue watch it, and the query's reclamation pass
        # closes registered iterators on cancellation
        from spark_rapids_tpu.engine.cancel import current_token
        from spark_rapids_tpu.utils import metrics as _M

        self._token = current_token()
        # registration is paired with DE-registration in close(): the
        # query's reclamation list must not hold strong references to
        # finished iterators (an abandoned-unclosed iterator would also
        # never be GC-collectable while its query runs)
        self._qctx = _M.current_query_ctx()
        if self._qctx is not None:
            self._qctx.prefetchers.append(self)
        # the reader decodes on behalf of the constructing task's QUERY:
        # carry its contextvars (per-tenant QueryContext — metrics, fault
        # injector — docs/serving.md) onto the worker thread
        cctx = contextvars.copy_context()
        self._thread = threading.Thread(
            target=cctx.run,
            args=(_prefetch_worker, source, self._queue, self._closed,
                  self._token),
            name=_THREAD_PREFIX + name, daemon=True)
        self._thread.start()

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self) -> T:
        if self._closed.is_set():
            raise StopIteration
        if self._tracer is not None:
            occ = self._queue.qsize()
            if occ > self._occ_high:
                self._occ_high = occ
        while True:
            # bounded poll: each wakeup re-checks close and the query's
            # CancelToken, so a cancelled consumer raises promptly
            # instead of outwaiting a dead reader
            try:
                kind, payload = self._queue.get(timeout=_POLL_S)
                break
            except queue.Empty:
                if self._closed.is_set():
                    raise StopIteration from None
                if self._token is not None:
                    try:
                        self._token.check("prefetch")
                    except BaseException:
                        self.close()
                        raise
        if payload is _END:
            self.close()
            raise StopIteration
        if kind == "error":
            self.close()
            raise payload
        self._items += 1
        return payload

    def close(self, join_timeout_s: float = _JOIN_S) -> None:
        """Stop the worker and JOIN its thread (bounded); safe to call
        multiple times / concurrently. The join is the satellite-bugfix
        contract: abandoning an unexhausted scan leaves ZERO live reader
        threads — the worker observes `closed` within one put/poll
        period, so the bound only trips if a source read itself wedges."""
        self._closed.set()
        # unblock a worker waiting on a full queue
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=max(0.0, join_timeout_s))
        qctx = self._qctx
        if qctx is not None:
            self._qctx = None
            try:
                qctx.prefetchers.remove(self)
            except ValueError:
                pass  # already deregistered (reclamation raced close)
        if self._tracer is not None and not self._reported:
            self._reported = True
            from spark_rapids_tpu.obs.trace import wall_ns

            self._tracer.note_span(
                f"prefetch:{self._name}", self._start_ns, wall_ns(),
                attrs={"depth": self._depth, "items": self._items,
                       "occupancy_high_water": self._occ_high},
                parent=self._parent_span)

    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            self.close()
        except Exception:
            pass


def maybe_prefetch(source: Iterator[T], depth: int) -> Iterator[T]:
    """`source` staged `depth` ahead on a worker thread, or `source`
    itself when depth <= 0 (prefetch disabled)."""
    if depth <= 0:
        return source
    return PrefetchIterator(source, depth)


def prefetch_depth(conf, split=None) -> int:
    """Effective prefetch depth for a scan: the per-read option
    (`prefetchBatches` on the reader) overrides the session conf."""
    from spark_rapids_tpu import conf as C

    depth = conf.get(C.IO_PREFETCH_BATCHES)
    if split is not None:
        override = split.opt("prefetchBatches")
        if override is not None:
            depth = int(override)
    return max(0, min(16, int(depth)))
