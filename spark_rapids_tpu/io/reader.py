"""DataFrameReader (the spark.read analog; reference: GpuReadParquet/Orc/
CSVFileFormat + Gpu*Scan schema handling)."""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

import pyarrow as pa

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.io.arrow_convert import schema_attrs
from spark_rapids_tpu.io.scan import _SUFFIXES, _to_bool, expand_paths
from spark_rapids_tpu.ops.base import AttributeReference
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.dataframe import DataFrame


class DataFrameReader:
    def __init__(self, session):
        self._session = session
        self._options: Dict[str, Any] = {}
        self._schema: Optional[List[AttributeReference]] = None

    def option(self, key: str, value: Any) -> "DataFrameReader":
        """Set a read option. Besides the format options (header/sep/
        inferSchema), `prefetchBatches` overrides the session's
        rapids.tpu.io.prefetchBatches scan double-buffering depth for
        THIS read only (0 disables prefetch; docs/async-execution.md)."""
        self._options[key] = value
        return self

    def options(self, **kwargs) -> "DataFrameReader":
        self._options.update(kwargs)
        return self

    def schema(self, schema) -> "DataFrameReader":
        """schema: list of (name, type-name-or-DataType) tuples."""
        attrs = []
        for name, t in schema:
            dt = t if isinstance(t, DataType) else DataType.parse(t)
            attrs.append(AttributeReference(name, dt, True))
        self._schema = attrs
        return self

    # -- formats --------------------------------------------------------------
    def parquet(self, *paths: str) -> DataFrame:
        return self._load("parquet", list(paths))

    def orc(self, *paths: str) -> DataFrame:
        return self._load("orc", list(paths))

    def csv(self, *paths: str, header: Optional[bool] = None,
            sep: Optional[str] = None,
            inferSchema: Optional[bool] = None) -> DataFrame:
        if header is not None:
            self._options["header"] = header
        if sep is not None:
            self._options["sep"] = sep
        if inferSchema is not None:
            self._options["inferSchema"] = inferSchema
        return self._load("csv", list(paths))

    def format(self, fmt: str) -> "_FormatReader":
        return _FormatReader(self, fmt)

    # -- schema resolution ----------------------------------------------------
    def _load(self, fmt: str, paths: List[str]) -> DataFrame:
        files = None
        if self._schema:
            attrs = self._schema
        else:
            attrs, files = self._resolve_schema(fmt, paths)
        plan = L.FileScan(fmt, paths, attrs, dict(self._options),
                          files=files)
        return DataFrame(plan, self._session)

    def _resolve_schema(self, fmt: str, paths: List[str]):
        # one directory walk serves both the file schema sample and the
        # Hive-style partition discovery (reference:
        # ColumnarPartitionReaderWithPartitionValues + Spark's inference)
        from spark_rapids_tpu.io.scan import (
            infer_partition_schema,
            partition_values_of,
        )

        files = expand_paths(paths, _SUFFIXES.get(fmt, ()))
        file_attrs = self._resolve_file_schema(fmt, files[0])
        part_attrs = infer_partition_schema(
            [partition_values_of(f, paths) for f in files])
        names = {a.name for a in file_attrs}
        return (file_attrs +
                [a for a in part_attrs if a.name not in names], files)

    def _resolve_file_schema(self, fmt: str,
                             sample: str) -> List[AttributeReference]:
        if fmt == "parquet":
            import pyarrow.parquet as pq

            return schema_attrs(pq.ParquetFile(sample).schema_arrow)
        if fmt == "orc":
            import pyarrow.orc as po

            return schema_attrs(po.ORCFile(sample).schema)
        if fmt == "csv":
            import pyarrow.csv as pc

            header = _to_bool(self._options.get("header", False))
            sep = self._options.get("sep",
                                    self._options.get("delimiter", ","))
            infer = _to_bool(self._options.get("inferSchema", False))
            # stream only the first block — never parse the whole file just
            # to learn the schema
            read_opts = pc.ReadOptions(autogenerate_column_names=not header)
            with pc.open_csv(
                    sample, read_options=read_opts,
                    parse_options=pc.ParseOptions(delimiter=sep)) as reader:
                first = reader.read_next_batch()
            if infer:
                return schema_attrs(first.schema)
            # Spark default: everything is a string unless inferSchema
            return [AttributeReference(n, DataType.STRING, True)
                    for n in first.schema.names]
        raise ValueError(f"unknown format {fmt}")


class _FormatReader:
    def __init__(self, reader: DataFrameReader, fmt: str):
        self._reader = reader
        self._fmt = fmt

    def option(self, k, v) -> "_FormatReader":
        self._reader.option(k, v)
        return self

    def load(self, *paths: str) -> DataFrame:
        return self._reader._load(self._fmt, list(paths))
