"""Device-side ORC encode (write path).

Reference parity: the reference encodes ORC ON the accelerator into a host
buffer and only streams bytes afterwards (`ColumnarOutputWriter.scala:
62-177` — cudf `Table.writeORC` under the semaphore,
`GpuOrcFileFormat.scala`). Mirrors the parquet device encoder
(io/parquet_encode_device.py) with ORC's stream model:

- DEVICE (data plane): per column, jitted kernels compact the non-null
  values, zigzag-encode, and big-endian bit-pack them into the RLEv2
  DIRECT payload; the validity bitmap bit-packs into the PRESENT bytes.
  What downloads is the *encoded* stream payload, not padded columns.
- HOST (control plane, tiny): interleaves the per-512-value DIRECT run
  headers and per-128-byte PRESENT literal headers, and writes the
  protobuf metadata (StripeFooter / Footer / PostScript). No value is
  touched on the host.

Scope: flat SHORT/INT/LONG/DATE columns (DIRECT_V2 with a single
column-wide bit width), STRING (DIRECT_V2: device byte-gather DATA +
RLEv2 LENGTH), FLOAT/DOUBLE (raw IEEE LE streams; DOUBLE needs an
f64-capable backend); one stripe per input batch. Streams and metadata
sections optionally host-compressed in ORC's 3-byte-header block framing
(zlib/snappy — the same codecs the device decoder's host control plane
uses). Files read back with pyarrow.orc and this repo's own device ORC
decoder. Everything else uses the host Arrow writer.
"""

from __future__ import annotations

import functools
import struct
from typing import List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.dtypes import DataType

# ORC type kinds (orc_proto Type.Kind)
_KIND = {
    DataType.BOOL: 0,    # BOOLEAN
    DataType.INT16: 2,   # SHORT
    DataType.INT32: 3,   # INT
    DataType.INT64: 4,   # LONG
    DataType.DATE: 15,   # DATE
    DataType.FLOAT32: 5,   # FLOAT
    DataType.FLOAT64: 6,   # DOUBLE
    DataType.STRING: 7,    # STRING
}
_INT_DTS = (DataType.INT16, DataType.INT32, DataType.INT64, DataType.DATE)
_K_STRUCT = 12

# PostScript CompressionKind
_COMP = {"none": 0, "uncompressed": 0, "zlib": 1, "snappy": 2}
_COMP_BLOCK = 64 * 1024

# RLEv2 DIRECT width -> 5-bit width code (subset: the widths we emit)
_DIRECT_WIDTHS = [1, 2, 4, 8, 16, 24, 32, 40, 48, 56, 64]
_WIDTH_CODE = {1: 0, 2: 1, 4: 3, 8: 7, 16: 15, 24: 23, 32: 27, 40: 28,
               48: 29, 56: 30, 64: 31}

_RUN = 512           # values per DIRECT run (max RLEv2 run length)
_LIT = 128           # bytes per PRESENT literal run


def schema_encodable(attrs) -> bool:
    from spark_rapids_tpu.columnar.batch import device_float64_supported

    for a in attrs:
        if a.data_type not in _KIND:
            return False
        if a.data_type is DataType.FLOAT64 and \
                not device_float64_supported():
            return False
    return True


def codec_supported(compression: str) -> bool:
    name = compression.lower()
    if name not in _COMP:
        return False
    if _COMP[name] == 2:  # snappy via the same pyarrow codec the decoder uses
        try:
            import pyarrow as pa

            pa.Codec("snappy")
        except Exception:
            return False
    return True


def _compress_stream(payload: bytes, kind: int) -> bytes:
    """Wrap a stream/metadata payload in ORC's compressed-block framing:
    3-byte little-endian header (len << 1 | is_original) per <=64KB block.
    HOST control plane — the mirror of decompress_blocks in the device
    decoder (orc_device.py)."""
    if kind == 0:
        return payload
    out = bytearray()
    for i in range(0, len(payload), _COMP_BLOCK):
        chunk = payload[i:i + _COMP_BLOCK]
        if kind == 1:
            import zlib

            c = zlib.compressobj(6, zlib.DEFLATED, -15)
            comp = c.compress(chunk) + c.flush()
        else:
            import pyarrow as pa

            comp = bytes(pa.Codec("snappy").compress(chunk))
        if len(comp) < len(chunk):
            h = len(comp) << 1
            out += bytes((h & 0xFF, (h >> 8) & 0xFF, (h >> 16) & 0xFF))
            out += comp
        else:
            h = (len(chunk) << 1) | 1
            out += bytes((h & 0xFF, (h >> 8) & 0xFF, (h >> 16) & 0xFF))
            out += chunk
    return bytes(out)


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------
@jax.jit
def _compact_zigzag(data, validity, num_rows):
    """Dense non-null values in row order, zigzag-encoded to uint64, plus
    the present count and the max encoded value (for the width pick).
    Validity is row-masked first — padding lanes must never contribute
    (same guard as the parquet encoder, parquet_encode_device.py)."""
    validity = validity & (jnp.arange(validity.shape[0]) < num_rows)
    order = jnp.argsort(~validity, stable=True)
    dense = data.astype(jnp.int64)[order]
    u = ((dense << 1) ^ (dense >> 63)).astype(jnp.uint64)
    n = jnp.sum(validity.astype(jnp.int32))
    in_range = jnp.arange(u.shape[0]) < n
    u = jnp.where(in_range, u, 0)
    return u, n, jnp.max(u)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _bitpack_be(u, width: int, out_bytes: int):
    """Big-endian bit-pack: value i occupies bits [i*width, (i+1)*width),
    MSB first — the RLEv2 DIRECT payload layout."""
    nvals = u.shape[0]
    byte_i = jnp.arange(out_bytes, dtype=jnp.int64)
    gb = byte_i[:, None] * 8 + jnp.arange(8, dtype=jnp.int64)[None, :]
    val_idx = gb // width
    shift = (width - 1 - (gb % width)).astype(jnp.uint64)
    vals = u[jnp.clip(val_idx, 0, nvals - 1)]
    vals = jnp.where(val_idx < nvals, vals, 0)
    bits = ((vals >> shift) & jnp.uint64(1)).astype(jnp.uint32)
    weights = (jnp.uint32(1) << (7 - jnp.arange(8, dtype=jnp.uint32)))
    return jnp.sum(bits * weights[None, :], axis=1).astype(jnp.uint8)


@jax.jit
def _pack_present(validity, num_rows):
    """PRESENT bitmap bytes: MSB-first, 1 = value present; bits beyond
    num_rows are zero-padded."""
    cap = validity.shape[0]
    nbytes = (cap + 7) // 8
    idx = jnp.arange(nbytes)[:, None] * 8 + jnp.arange(8)[None, :]
    ok = (idx < num_rows) & validity[jnp.clip(idx, 0, cap - 1)]
    weights = (jnp.uint32(1) << (7 - jnp.arange(8, dtype=jnp.uint32)))
    return jnp.sum(ok.astype(jnp.uint32) * weights[None, :],
                   axis=1).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Host control plane: headers + protobuf
# ---------------------------------------------------------------------------
def _pick_width(max_u: int) -> int:
    need = max(int(max_u).bit_length(), 1)
    for w in _DIRECT_WIDTHS:
        if w >= need:
            return w
    return 64


def _direct_stream(packed: bytes, n: int, width: int) -> bytes:
    """Interleave the 2-byte DIRECT run headers between the contiguous
    512-value byte-aligned payload chunks the device produced."""
    out = bytearray()
    run_bytes = _RUN * width // 8
    for r in range((n + _RUN - 1) // _RUN):
        length = min(_RUN, n - r * _RUN)
        h1 = 0x40 | (_WIDTH_CODE[width] << 1) | ((length - 1) >> 8)
        h2 = (length - 1) & 0xFF
        out.append(h1)
        out.append(h2)
        chunk = packed[r * run_bytes:
                       r * run_bytes + (length * width + 7) // 8]
        out += chunk
    return bytes(out)


def _present_stream(bitmap: bytes) -> bytes:
    """Byte-RLE literal runs over the bitmap bytes (header = -count)."""
    out = bytearray()
    for i in range(0, len(bitmap), _LIT):
        chunk = bitmap[i:i + _LIT]
        out.append(256 - len(chunk))
        out += chunk
    return bytes(out)


# varint shared with the parquet thrift writer (same LEB128 wire format)
from spark_rapids_tpu.io.parquet_encode_device import _uvarint  # noqa: E402


def _fv(fnum: int, v: int) -> bytes:
    return _uvarint((fnum << 3) | 0) + _uvarint(v)


def _fb(fnum: int, b: bytes) -> bytes:
    return _uvarint((fnum << 3) | 2) + _uvarint(len(b)) + b


@jax.jit
def _compact_fixed(data, validity, num_rows):
    """Dense non-null values in row order (no transform — FLOAT/DOUBLE
    raw IEEE streams)."""
    validity = validity & (jnp.arange(validity.shape[0]) < num_rows)
    order = jnp.argsort(~validity, stable=True)
    return data[order], jnp.sum(validity.astype(jnp.int32))


@functools.partial(jax.jit, static_argnums=(2,))
def _lens_u64(lens, n_present, cap: int):
    """Unsigned length stream values for RLEv2 (no zigzag — LENGTH is
    unsigned per the ORC spec)."""
    in_sel = jnp.arange(cap) < n_present
    u = jnp.where(in_sel, lens, 0).astype(jnp.uint64)
    return u, jnp.max(u)


def _rle_direct(u, n: int, max_u: int) -> bytes:
    width = _pick_width(max_u)
    if n <= 0:
        return b""
    out_bytes = ((n + _RUN - 1) // _RUN) * (_RUN * width // 8)
    packed = bytes(np.asarray(jax.device_get(
        _bitpack_be(u, width, out_bytes))))
    return _direct_stream(packed, n, width)


def _encode_stripe(attrs, batch: ColumnarBatch,
                   comp_kind: int) -> Tuple[bytes, bytes, int]:
    """One input batch -> (stripe data bytes, stripe footer bytes, rows).
    Stream payloads are device-encoded then host-compressed per block."""
    from spark_rapids_tpu.columnar.batch import (
        bucket_capacity,
        ensure_compact,
    )
    from spark_rapids_tpu.io.parquet_encode_device import (
        _encode_string_bytes,
        _encode_string_plan,
    )

    # live-masked batches (exchange outputs) compact first: the PRESENT
    # bitmap is positional over the stripe's rows, so lanes 0..n_rows-1
    # must BE the rows
    batch = ensure_compact(batch)
    n_rows = int(batch.host_rows())
    streams: List[Tuple[int, int, bytes]] = []   # (kind, column, payload)
    for ci, a in enumerate(attrs):
        cv = batch.columns[ci]
        validity = cv.validity
        dt = a.data_type
        if dt is DataType.STRING:
            cap = validity.shape[0]
            sel, lens, out_offsets, n, total = _encode_string_plan(
                cv.data, cv.offsets, validity, jnp.int32(n_rows), cap, 0)
            n = int(jax.device_get(n))
            total = int(jax.device_get(total))
            if n != n_rows:
                bitmap = bytes(np.asarray(jax.device_get(
                    _pack_present(validity, jnp.int32(n_rows)))))
                streams.append((0, ci + 1,
                                _present_stream(bitmap[:(n_rows + 7) // 8])))
            byte_cap = bucket_capacity(max(total, 1))
            sbytes = _encode_string_bytes(cv.data, cv.offsets, sel, lens,
                                          out_offsets, byte_cap, 0)
            data = bytes(np.asarray(jax.device_get(sbytes[:total])))
            streams.append((1, ci + 1, data))
            u, max_u = _lens_u64(lens, jnp.int32(n), cap)
            max_u = int(jax.device_get(max_u))
            streams.append((2, ci + 1, _rle_direct(u, n, max_u)))
            continue
        if dt is DataType.BOOL:
            # BOOLEAN DATA: dense values bit-packed MSB-first in the same
            # byte-RLE literal framing as PRESENT
            dense, n = _compact_fixed(cv.data, validity, jnp.int32(n_rows))
            n = int(jax.device_get(n))
            if n != n_rows:
                bitmap = bytes(np.asarray(jax.device_get(
                    _pack_present(validity, jnp.int32(n_rows)))))
                streams.append((0, ci + 1,
                                _present_stream(bitmap[:(n_rows + 7) // 8])))
            vbits = bytes(np.asarray(jax.device_get(
                _pack_present(dense.astype(bool), jnp.int32(n)))))
            streams.append((1, ci + 1,
                            _present_stream(vbits[:(n + 7) // 8])))
            continue
        if dt in (DataType.FLOAT32, DataType.FLOAT64):
            dense, n = _compact_fixed(cv.data, validity, jnp.int32(n_rows))
            n = int(jax.device_get(n))
            if n != n_rows:
                bitmap = bytes(np.asarray(jax.device_get(
                    _pack_present(validity, jnp.int32(n_rows)))))
                streams.append((0, ci + 1,
                                _present_stream(bitmap[:(n_rows + 7) // 8])))
            host = np.asarray(jax.device_get(dense[:n]))
            want = np.float32 if dt is DataType.FLOAT32 else np.float64
            streams.append((1, ci + 1,
                            host.astype(want, copy=False).tobytes()))
            continue
        u, n, max_u = _compact_zigzag(cv.data, validity,
                                      jnp.int32(n_rows))
        n, max_u = int(jax.device_get(n)), int(jax.device_get(max_u))
        if n != n_rows:
            bitmap = bytes(np.asarray(
                jax.device_get(_pack_present(validity,
                                             jnp.int32(n_rows)))))
            bitmap = bitmap[:(n_rows + 7) // 8]
            streams.append((0, ci + 1, _present_stream(bitmap)))
        streams.append((1, ci + 1, _rle_direct(u, n, max_u)))

    data_area = bytearray()
    footer = bytearray()
    for kind, col, payload in streams:
        wire = _compress_stream(payload, comp_kind)
        data_area += wire
        footer += _fb(1, _fv(1, kind) + _fv(2, col) + _fv(3, len(wire)))
    # column encodings: root struct DIRECT; ints/strings DIRECT_V2,
    # floats DIRECT
    footer += _fb(2, _fv(1, 0))
    for a in attrs:
        enc = 0 if a.data_type in (DataType.FLOAT32, DataType.FLOAT64,
                                   DataType.BOOL) else 2
        footer += _fb(2, _fv(1, enc))
    return bytes(data_area), bytes(footer), n_rows


def write_file(path: str, attrs, batches: List[ColumnarBatch],
               compression: str = "uncompressed") -> int:
    """Assemble one ORC file from device-encoded stripes (one stripe per
    batch); streams and metadata sections are host-block-compressed when
    a codec is requested. Returns rows written."""
    comp_kind = _COMP[compression.lower()]
    header = b"ORC"
    body = bytearray(header)
    stripe_infos: List[Tuple[int, int, int, int]] = []
    total_rows = 0
    for b in batches:
        if b.host_rows() == 0:
            continue
        offset = len(body)
        data, sfooter, rows = _encode_stripe(attrs, b, comp_kind)
        sfooter = _compress_stream(sfooter, comp_kind)
        body += data
        body += sfooter
        stripe_infos.append((offset, len(data), len(sfooter), rows))
        total_rows += rows

    # Footer
    footer = bytearray()
    footer += _fv(1, len(header))          # headerLength
    footer += _fv(2, len(body))            # contentLength
    for off, dlen, flen, rows in stripe_infos:
        footer += _fb(3, _fv(1, off) + _fv(2, 0) + _fv(3, dlen)
                      + _fv(4, flen) + _fv(5, rows))
    # types: root struct + one per column
    root = _fv(1, _K_STRUCT)
    for ci, a in enumerate(attrs):
        root += _fv(2, ci + 1)
    for a in attrs:
        root += _fb(3, a.name.encode("utf-8"))
    footer += _fb(4, root)
    for a in attrs:
        footer += _fb(4, _fv(1, _KIND[a.data_type]))
    footer += _fv(6, total_rows)           # numberOfRows
    footer += _fv(8, 0)                    # rowIndexStride: no row index
    footer = bytearray(_compress_stream(bytes(footer), comp_kind))

    ps = bytearray()
    ps += _fv(1, len(footer))              # footerLength
    ps += _fv(2, comp_kind)                # compression kind
    ps += _fv(3, _COMP_BLOCK)              # compressionBlockSize
    ps += _uvarint((4 << 3) | 0) + _uvarint(0)    # version: 0
    ps += _uvarint((4 << 3) | 0) + _uvarint(12)   # version: 12
    ps += _fv(5, 0)                        # metadataLength
    ps += _fv(6, 1)                        # writerVersion
    ps += _fb(8000, b"ORC")                # magic
    assert len(ps) < 256

    with open(path, "wb") as f:
        f.write(bytes(body))
        f.write(bytes(footer))
        f.write(bytes(ps))
        f.write(struct.pack("B", len(ps)))
    return total_rows
