"""ctypes binding for the native host control-plane kernels.

The .so builds from srt_native.cpp on first import when a compiler is
available (build product is cached next to the source); every entry point
has a pure-Python fallback, so the framework works without a toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "srt_native.cpp")
_SO = os.path.join(_DIR, "_srt_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    import shutil

    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        return False
    try:
        subprocess.run(
            [gxx, "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # noqa: BLE001
        log.info("native build skipped: %s", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None when
    unavailable (callers use their Python fallback)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.info("native load failed: %s", e)
            return None
        try:
            _bind(lib)
        except AttributeError as e:
            # stale cached .so predating a newly added symbol (mtime-equal
            # copies skip the rebuild): fall back to pure Python
            log.info("native lib stale (%s); using Python fallbacks", e)
            return None
        _lib = lib
        return _lib


def _bind(lib) -> None:
    lib.srt_parse_runs.restype = ctypes.c_int64
    lib.srt_parse_runs.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.srt_parse_pages.restype = ctypes.c_int64
    lib.srt_parse_pages.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.srt_plain_strings.restype = ctypes.c_int64
    lib.srt_plain_strings.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.srt_csv_plan.restype = ctypes.c_int64
    lib.srt_csv_plan.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint8,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
    ]
