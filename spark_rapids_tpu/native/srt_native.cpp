// Native host-side control-plane kernels.
//
// Reference parity: the reference's host control plane is C++/JVM-native
// (parquet-mr page walking + cudf's C++ RLE machinery feeding the GPU
// decoder, GpuParquetScan.scala:316-458). Here the TPU framework keeps the
// same split: the device data plane is XLA, and these byte-level host loops
// — RLE/bit-packed run-table extraction and serialized-batch string offset
// encoding — run natively instead of interpreting bytes in Python.
//
// Built as a plain shared object; Python binds via ctypes
// (spark_rapids_tpu/native/__init__.py) and falls back to the pure-Python
// implementations when the .so is absent.

#include <cstdint>
#include <cstring>

extern "C" {

// Parse one parquet RLE/bit-packed hybrid stream into a run table.
// Returns the number of runs written, or -1 if max_runs was too small,
// -2 on a malformed varint.
//
//   buf[start:end) : the raw chunk bytes containing the hybrid stream
//   bit_width      : value bit width (dict index width or 1 for def levels)
//   num_values     : logical values to account for
//   out_start[i]   : output index where run i begins
//   is_rle[i]      : 1 = RLE run (value[i] repeated), 0 = bit-packed
//   value[i]       : the repeated value for RLE runs
//   bit_off[i]     : absolute BIT offset of packed values for bp runs
int64_t srt_parse_runs(const uint8_t* buf, int64_t start, int64_t end,
                       int32_t bit_width, int64_t num_values,
                       int64_t* out_start, uint8_t* is_rle, int32_t* value,
                       int64_t* bit_off, int64_t max_runs,
                       int64_t* produced_out) {
    int64_t pos = start;
    int64_t produced = 0;
    int64_t n = 0;
    const int32_t vbytes = (bit_width + 7) / 8;
    while (produced < num_values && pos < end) {
        // LEB128 varint header
        uint64_t header = 0;
        int shift = 0;
        for (;;) {
            if (pos >= end || shift > 63) return -2;
            uint8_t b = buf[pos++];
            header |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        if (n >= max_runs) return -1;
        if (header & 1) {  // bit-packed: (header>>1) groups of 8 values
            int64_t groups = (int64_t)(header >> 1);
            out_start[n] = produced;
            is_rle[n] = 0;
            value[n] = 0;
            bit_off[n] = pos * 8;
            pos += groups * bit_width;
            produced += groups * 8;
        } else {           // RLE: (header>>1) copies of one LE value
            int64_t count = (int64_t)(header >> 1);
            // accumulate unsigned: shifting into the sign bit of a signed
            // int is UB; a single cast at the end is well-defined
            uint32_t uv = 0;
            for (int32_t k = 0; k < vbytes && pos + k < end; ++k)
                uv |= (uint32_t)buf[pos + k] << (8 * k);
            int32_t v = (int32_t)uv;
            pos += vbytes;
            out_start[n] = produced;
            is_rle[n] = 1;
            value[n] = v;
            bit_off[n] = 0;
            produced += count;
        }
        ++n;
    }
    *produced_out = produced;
    return n;
}

}  // extern "C"
