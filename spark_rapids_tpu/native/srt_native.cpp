// Native host-side control-plane kernels.
//
// Reference parity: the reference's host control plane is C++/JVM-native
// (parquet-mr page walking + cudf's C++ RLE machinery feeding the GPU
// decoder, GpuParquetScan.scala:316-458; cudf's C++ CSV tokenizer feeding
// the device parser, GpuBatchScanExec.scala:322-520). Here the TPU
// framework keeps the same split: the device data plane is XLA, and these
// byte-level host loops — RLE/bit-packed run-table extraction, thrift
// page-header walking, and CSV field-boundary scanning — run natively
// instead of interpreting bytes in Python.
//
// Built as a plain shared object; Python binds via ctypes
// (spark_rapids_tpu/native/__init__.py) and falls back to the pure-Python
// implementations when the .so is absent.

#include <cstdint>
#include <cstring>

// ---------------------------------------------------------------------------
// Thrift compact-protocol reader (just enough for parquet PageHeader).
// ---------------------------------------------------------------------------
namespace {

struct Reader {
    const uint8_t* buf;
    int64_t pos;
    int64_t end;
    bool err = false;

    uint64_t varint() {
        uint64_t out = 0;
        int shift = 0;
        for (;;) {
            if (pos >= end || shift > 63) { err = true; return 0; }
            uint8_t b = buf[pos++];
            out |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) return out;
            shift += 7;
        }
    }

    int64_t zigzag() {
        uint64_t v = varint();
        return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
    }

    void skip_value(int ftype);

    // Parse a struct, reporting (fid, ftype) to `cb`; the callback returns
    // true when it consumed the value itself (possibly recursing).
    template <typename F>
    void parse_struct(F&& cb) {
        int64_t fid = 0;
        for (;;) {
            if (pos >= end) { err = true; return; }
            uint8_t b = buf[pos++];
            if (b == 0) return;
            int delta = b >> 4;
            int ftype = b & 0x0F;
            fid = delta ? fid + delta : zigzag();
            if (err) return;
            if (!cb(fid, ftype, *this)) skip_value(ftype);
            if (err) return;
        }
    }
};

void Reader::skip_value(int ftype) {
    // every length below is validated against the remaining bytes BEFORE
    // advancing — corrupt varints must never move `pos` backward or spin
    // (the python fallback throws on the same inputs; native must too)
    switch (ftype) {
        case 1: case 2: return;            // bool encoded in the type
        case 3: ++pos; return;             // i8
        case 4: case 5: case 6: zigzag(); return;
        case 7: pos += 8; return;          // double
        case 8: {                          // binary/string
            uint64_t n = varint();
            if (err || n > (uint64_t)(end - pos)) { err = true; return; }
            pos += (int64_t)n;
            return;
        }
        case 9: case 10: {                 // list/set
            if (pos >= end) { err = true; return; }
            uint8_t b = buf[pos++];
            uint64_t n = b >> 4;
            int et = b & 0x0F;
            if (n == 15) n = varint();
            if (err) return;
            if (et == 1 || et == 2) return;  // bools consume no bytes
            // each remaining element consumes >= 1 byte; a count beyond
            // the buffer is malformed, not a long loop
            if (n > (uint64_t)(end - pos)) { err = true; return; }
            for (uint64_t i = 0; i < n && !err; ++i) skip_value(et);
            return;
        }
        case 12:                           // struct
            parse_struct([](int64_t, int, Reader&) { return false; });
            return;
        default:
            err = true;
    }
}

}  // namespace

extern "C" {

// Parse one parquet RLE/bit-packed hybrid stream into a run table.
// Returns the number of runs written, or -1 if max_runs was too small,
// -2 on a malformed varint.
//
//   buf[start:end) : the raw chunk bytes containing the hybrid stream
//   bit_width      : value bit width (dict index width or 1 for def levels)
//   num_values     : logical values to account for
//   out_start[i]   : output index where run i begins
//   is_rle[i]      : 1 = RLE run (value[i] repeated), 0 = bit-packed
//   value[i]       : the repeated value for RLE runs
//   bit_off[i]     : absolute BIT offset of packed values for bp runs
int64_t srt_parse_runs(const uint8_t* buf, int64_t start, int64_t end,
                       int32_t bit_width, int64_t num_values,
                       int64_t* out_start, uint8_t* is_rle, int32_t* value,
                       int64_t* bit_off, int64_t max_runs,
                       int64_t* produced_out) {
    int64_t pos = start;
    int64_t produced = 0;
    int64_t n = 0;
    const int32_t vbytes = (bit_width + 7) / 8;
    while (produced < num_values && pos < end) {
        // LEB128 varint header
        uint64_t header = 0;
        int shift = 0;
        for (;;) {
            if (pos >= end || shift > 63) return -2;
            uint8_t b = buf[pos++];
            header |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        if (n >= max_runs) return -1;
        if (header & 1) {  // bit-packed: (header>>1) groups of 8 values
            int64_t groups = (int64_t)(header >> 1);
            // a group count whose bytes run past the stream is malformed —
            // reject before the multiply can overflow or move pos wild
            if (groups < 0 || (bit_width > 0 &&
                               groups > (end - pos) / bit_width + 1))
                return -2;
            out_start[n] = produced;
            is_rle[n] = 0;
            value[n] = 0;
            bit_off[n] = pos * 8;
            pos += groups * bit_width;
            produced += groups * 8;
        } else {           // RLE: (header>>1) copies of one LE value
            int64_t count = (int64_t)(header >> 1);
            // accumulate unsigned: shifting into the sign bit of a signed
            // int is UB; a single cast at the end is well-defined
            uint32_t uv = 0;
            for (int32_t k = 0; k < vbytes && pos + k < end; ++k)
                uv |= (uint32_t)buf[pos + k] << (8 * k);
            int32_t v = (int32_t)uv;
            pos += vbytes;
            out_start[n] = produced;
            is_rle[n] = 1;
            value[n] = v;
            bit_off[n] = 0;
            produced += count;
        }
        ++n;
    }
    *produced_out = produced;
    return n;
}

// Walk the page headers of one raw column chunk (python fallback:
// io/parquet_device.py parse_pages). Returns the page count or
//   -1 : max_pages too small      -2 : malformed thrift
//   -4 : unsupported page type (v2 etc.) — caller falls back to Arrow
int64_t srt_parse_pages(const uint8_t* buf, int64_t len,
                        int32_t* kind, int64_t* num_values,
                        int32_t* encoding, int64_t* data_start,
                        int64_t* data_len, int64_t max_pages) {
    int64_t n = 0;
    int64_t pos = 0;
    while (pos < len) {
        Reader r{buf, pos, len};
        int64_t ph_type = -1, ph_comp = -1;
        int64_t dp_num = -1, dp_enc = -1, di_num = -1;
        r.parse_struct([&](int64_t fid, int ftype, Reader& rr) {
            if (fid == 1 && ftype >= 4 && ftype <= 6) {        // page type
                ph_type = rr.zigzag();
                return true;
            }
            if (fid == 3 && ftype >= 4 && ftype <= 6) {        // comp. size
                ph_comp = rr.zigzag();
                return true;
            }
            if (fid == 5 && ftype == 12) {                     // data v1 hdr
                rr.parse_struct([&](int64_t f2, int t2, Reader& r2) {
                    if (f2 == 1 && t2 >= 4 && t2 <= 6) {
                        dp_num = r2.zigzag();
                        return true;
                    }
                    if (f2 == 2 && t2 >= 4 && t2 <= 6) {
                        dp_enc = r2.zigzag();
                        return true;
                    }
                    return false;
                });
                return true;
            }
            if (fid == 7 && ftype == 12) {                     // dict hdr
                rr.parse_struct([&](int64_t f2, int t2, Reader& r2) {
                    if (f2 == 1 && t2 >= 4 && t2 <= 6) {
                        di_num = r2.zigzag();
                        return true;
                    }
                    return false;
                });
                return true;
            }
            return false;
        });
        if (r.err || ph_comp < 0 || ph_type < 0) return -2;
        if (ph_comp > len - r.pos) return -2;  // payload past the buffer
        if (n >= max_pages) return -1;
        if (ph_type == 2) {            // dictionary page
            kind[n] = 2;
            num_values[n] = di_num;
            encoding[n] = 0;           // dict payload reads as PLAIN
        } else if (ph_type == 0) {     // data page v1
            if (dp_num < 0 || dp_enc < 0) return -2;
            kind[n] = 0;
            num_values[n] = dp_num;
            encoding[n] = (int32_t)dp_enc;
        } else {
            return -4;
        }
        data_start[n] = r.pos;
        data_len[n] = ph_comp;
        ++n;
        pos = r.pos + ph_comp;
    }
    return n;
}

// Single-pass CSV field-boundary scan (the host control plane of the
// device CSV parser, io/csv_device.py). Replaces a multi-pass numpy scan
// with one cache-friendly sweep that simultaneously finds boundaries,
// validates column counts per line, rejects quoted fields, and trims CRLF.
//
// Returns the number of data rows written, or
//   -1 : structure not eligible (quote char seen, ragged line)
//   -3 : more rows than max_rows (caller re-allocates and retries)
//
//   starts/lens : int32 [max_rows * ncols], row-major
int64_t srt_csv_plan(const uint8_t* buf, int64_t len, uint8_t sep,
                     int32_t ncols, int32_t* starts, int32_t* lens,
                     int64_t max_rows) {
    if (len <= 0) return -1;
    int64_t row = 0;
    int32_t col = 0;
    int64_t field_start = 0;
    for (int64_t i = 0; i <= len; ++i) {
        const bool at_eof = (i == len);
        const uint8_t c = at_eof ? (uint8_t)'\n' : buf[i];
        if (c == (uint8_t)'"') return -1;
        if (c == sep || c == (uint8_t)'\n') {
            // EOF acts as a virtual newline only for a non-empty last line
            if (at_eof && col == 0 && field_start == i) break;
            if (c == sep) {
                if (col >= ncols - 1) return -1;  // too many fields
            } else {
                if (col != ncols - 1) return -1;  // too few fields
            }
            if (row >= max_rows) return -3;
            int32_t flen = (int32_t)(i - field_start);
            // trim a trailing \r before a newline (CRLF files)
            if (c == (uint8_t)'\n' && flen > 0 &&
                buf[i - 1] == (uint8_t)'\r')
                --flen;
            starts[row * ncols + col] = (int32_t)field_start;
            lens[row * ncols + col] = flen;
            field_start = i + 1;
            if (c == sep) {
                ++col;
            } else {
                col = 0;
                ++row;
            }
        }
    }
    if (col != 0) return -1;  // dangling partial line (shouldn't happen)
    return row;
}

// Walk a parquet PLAIN byte-array page: n values of (u32 LE length +
// bytes). Fills absolute starts/lens; returns n or -1 on truncation.
int64_t srt_plain_strings(const uint8_t* buf, int64_t pos, int64_t end,
                          int64_t n, int32_t* starts, int32_t* lens) {
  for (int64_t i = 0; i < n; i++) {
    if (pos + 4 > end) return -1;
    uint32_t ln = (uint32_t)buf[pos] | ((uint32_t)buf[pos + 1] << 8) |
                  ((uint32_t)buf[pos + 2] << 16) |
                  ((uint32_t)buf[pos + 3] << 24);
    pos += 4;
    if ((int64_t)ln > end - pos) return -1;
    starts[i] = (int32_t)pos;
    lens[i] = (int32_t)ln;
    pos += (int64_t)ln;
  }
  return n;
}

}  // extern "C"
