"""Device mesh + collective repartition primitives.

The ICI analog of the reference's UCX shuffle data plane
(UCXShuffleTransport.scala:47-507): rows move between shards with ONE
`lax.all_to_all` inside a jitted `shard_map`, instead of N^2 tagged
point-to-point sends. Bucketing is static-shape: each shard routes its rows
into `n_shards` fixed-capacity buckets (validity-masked), which is exactly
the bounce-buffer discipline of the reference (BounceBufferManager.scala)
recast as padded device arrays.

`distributed_agg_step` is the flagship multi-chip program: per-shard partial
aggregation -> all-to-all hash exchange -> per-shard final merge — the
partial/exchange/final call stack of SURVEY.md section 3.5 compiled into a
single XLA program spanning the mesh.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from spark_rapids_tpu.exec import rowkeys as RK
from spark_rapids_tpu.ops import hashing as H
from spark_rapids_tpu.ops.values import ColV
from spark_rapids_tpu.columnar.dtypes import DataType

DATA_AXIS = "data"


def build_mesh(n_devices: Optional[int] = None,
               axis: str = DATA_AXIS, devices=None) -> Mesh:
    """1-D mesh over the first n devices (the executor-per-chip analog of
    GpuDeviceManager's one-GPU-per-executor policy). An explicit device
    list overrides discovery — the quarantine-aware mesh rebuild
    (shuffle/ici.session_mesh) passes the surviving devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _route_to_buckets(data_cols: List[jnp.ndarray], validity, pid,
                      n_shards: int, bucket_cap: int):
    """Pack rows into n_shards fixed-size buckets by target shard id.

    Returns ([n_shards, bucket_cap] arrays per column, bucket validity).
    Rows beyond a bucket's capacity are dropped (callers size bucket_cap to
    make this impossible; the inflight-limit analog of the reference's
    maxBytesInFlight throttle).
    """
    cap = validity.shape[0]
    out_cols = []
    out_valid = []
    for t in range(n_shards):
        mask = validity & (pid == t)
        order = jnp.argsort(~mask, stable=True).astype(jnp.int32)
        sel = order[:bucket_cap]
        out_valid.append(mask[sel])
        out_cols.append([c[sel] for c in data_cols])
    bucket_valid = jnp.stack(out_valid)  # [n_shards, bucket_cap]
    stacked = [
        jnp.stack([out_cols[t][ci] for t in range(n_shards)])
        for ci in range(len(data_cols))
    ]
    return stacked, bucket_valid


def all_to_all_table(data_cols: List[jnp.ndarray], validity, pid,
                     n_shards: int, bucket_cap: int, axis: str = DATA_AXIS):
    """Shard-local body: route rows to per-target buckets and exchange them
    over the mesh axis. Returns per-column [n_shards*bucket_cap] arrays plus
    validity for the received rows. Must run inside shard_map."""
    stacked, bucket_valid = _route_to_buckets(data_cols, validity, pid,
                                              n_shards, bucket_cap)
    recv_cols = [
        jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)
        for s in stacked
    ]
    recv_valid = jax.lax.all_to_all(bucket_valid, axis, split_axis=0,
                                    concat_axis=0, tiled=True)
    # rank-2 columns (fixed-width string matrices) keep their trailing axis
    flat_cols = [c.reshape((-1,) + c.shape[2:]) for c in recv_cols]
    return flat_cols, recv_valid.reshape(-1)


def distributed_agg_step(mesh: Mesh, n_shards: int, cap: int,
                         bucket_cap: int, axis: str = DATA_AXIS):
    """Build the jitted multi-chip filter+project+groupby-sum step.

    Inputs (sharded on the leading axis over `axis`):
      keys   [n_shards, cap] int64
      values [n_shards, cap] int64
      valid  [n_shards, cap] bool
    Output (sharded the same way):
      group keys / sums / validity per shard [n_shards, n_shards*bucket_cap]
      plus the global group count (replicated via psum).
    """
    def per_shard(keys, values, valid):
        keys = keys[0]
        values = values[0]
        valid = valid[0]
        # -- scan-side: filter (values % 3 != 0) + project (v * 2 + 1) ------
        valid = valid & (values % 3 != 0)
        values = jnp.where(valid, values * 2 + 1, 0)
        keys = jnp.where(valid, keys, 0)

        # -- partial aggregate (update) -------------------------------------
        kcol = ColV(DataType.INT64, keys, valid)
        gi = RK.group_ids_masked([RK.key_proxy(kcol)], valid, cap)
        psum_, pvalid = RK.segment_reduce("sum", values, valid, gi,
                                          None, cap)
        pkeys = keys[gi.rep_rows]  # slot g holds group g's key
        slot = jnp.arange(cap) < gi.num_groups

        # -- hash exchange over ICI ----------------------------------------
        kv = ColV(DataType.INT64, pkeys, slot)
        pid = H.partition_ids(jnp, [kv], n_shards)
        (rk, rv), rvalid = all_to_all_table(
            [pkeys, psum_], slot & pvalid, pid, n_shards, bucket_cap, axis)

        # -- final merge aggregate ------------------------------------------
        rcap = rk.shape[0]
        rcol = ColV(DataType.INT64, jnp.where(rvalid, rk, 0), rvalid)
        gi2 = RK.group_ids_masked([RK.key_proxy(rcol)], rvalid, rcap)
        fsum, fvalid = RK.segment_reduce("sum", rv, rvalid, gi2,
                                         None, rcap)
        fkeys = rk[gi2.rep_rows]
        out_slot = jnp.arange(rcap) < gi2.num_groups
        total_groups = jax.lax.psum(gi2.num_groups, axis)
        return (fkeys[None], fsum[None], (out_slot & fvalid)[None],
                total_groups[None])

    spec = P(axis)
    smapped = shard_map(
        per_shard, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec, spec),
    )
    # tpulint: jit-cache -- built once per mesh; callers hold the step fn
    return jax.jit(smapped)
