"""Multi-host distributed backend: jax.distributed + global mesh + SPMD
data placement.

Reference parity: the role of the reference's multi-executor deployment —
executors on different hosts exchanging shuffle data over UCX/RDMA
(shuffle-plugin/.../ucx/UCX.scala:54-525 management handshake;
UCXShuffleTransport.scala:47-507 data plane). The TPU-native equivalent is
JAX's coordination service plus XLA collectives: every host runs the same
SPMD program over ONE global `Mesh` spanning all pod chips; `all_to_all`
and `psum` ride ICI inside a host/slice and DCN across hosts — the
transport selection the reference does by hand (IB verbs vs TCP,
UCXConnection.scala) is XLA's job here.

Bring-up mirrors `RapidsDriverPlugin`/`RapidsExecutorPlugin`
(Plugin.scala:103-142): one coordinator address, every process announces
itself, failure to initialize is fatal for the process so the scheduler
can replace it.

Usage (per process, before any other jax call):

    from spark_rapids_tpu.parallel import distributed as D
    D.init_distributed()            # env-driven; no-op single-process
    mesh = D.global_mesh()          # all chips, host-major order
    arr = D.shard_host_data(np_chunk, mesh)   # local rows -> global array

Env contract (also honors the standard JAX service env vars):
  SRT_COORDINATOR=host:port   SRT_NUM_PROCESSES=N   SRT_PROCESS_ID=i
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"

_LOCK = threading.Lock()
_INITIALIZED = False


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Join (or start, for process 0) the coordination service. Returns True
    when running multi-process, False for the single-process fast path.

    Must run before the first jax backend touch in this process. Fatal
    errors terminate the process — the reference executor plugin exits the
    JVM on init failure the same way (Plugin.scala:129-136) so the cluster
    scheduler reschedules it.
    """
    global _INITIALIZED
    with _LOCK:
        if _INITIALIZED:
            return True
        coordinator_address = coordinator_address or \
            os.environ.get("SRT_COORDINATOR")
        num_processes = num_processes if num_processes is not None else \
            int(os.environ.get("SRT_NUM_PROCESSES", "0") or 0)
        process_id = process_id if process_id is not None else \
            int(os.environ.get("SRT_PROCESS_ID", "-1"))
        if not coordinator_address or num_processes <= 1 or process_id < 0:
            return False
        platforms = (getattr(jax.config, "jax_platforms", None)
                     or os.environ.get("JAX_PLATFORMS", ""))
        if platforms.split(",")[0].strip().lower() in ("", "cpu"):
            # CPU-backend multi-process collectives need an explicit
            # implementation; without it XLA raises 'Multiprocess
            # computations aren't implemented on the CPU backend' at the
            # first collective (the multichip dryrun contract runs 2
            # processes x 4 virtual CPU devices through here). Keyed on
            # the RESOLVED platform preference — the config value set by
            # jax.config.update('jax_platforms', ...) wins over the env
            # spelling, 'cpu,tpu' counts, and an UNSET preference may
            # still auto-resolve to cpu, so it opts in too (the setting
            # only affects the CPU backend; harmless on real chips).
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        _INITIALIZED = True
        return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def global_mesh(axis: str = DATA_AXIS) -> Mesh:
    """1-D mesh over ALL pod devices, host-major: each host's chips are
    contiguous along the axis, so bucketed `all_to_all` moves intra-host
    traffic over ICI and only the cross-host remainder over DCN."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return Mesh(np.array(devs), (axis,))


def shard_host_data(local_rows: np.ndarray, mesh: Mesh,
                    axis: str = DATA_AXIS):
    """Place this process's host rows as its shards of one global array
    sharded along the leading dim (the analog of each executor contributing
    its map-output partitions). local_rows' leading dim must equal
    global_dim / process_count for even sharding."""
    sharding = NamedSharding(mesh, P(axis))
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows)


def replicate(value: np.ndarray, mesh: Mesh):
    """Broadcast small host data to every device (the TorrentBroadcast
    analog, GpuBroadcastExchangeExec.scala:47-200 — XLA replication over
    ICI/DCN instead of BitTorrent over TCP)."""
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    return jax.make_array_from_process_local_data(sharding, value)
