"""Multi-chip parallel execution: device mesh + ICI collective shuffle.

Reference parity: SURVEY.md section 2.8 tier B — the UCX peer-to-peer shuffle
(shuffle-plugin/.../ucx/, 1,788 LoC of tag-matched RDMA) mapped to the TPU
fabric the idiomatic way: a `jax.sharding.Mesh` over the pod slice, with the
repartition step expressed as a jitted `shard_map` whose `lax.all_to_all`
rides ICI (and DCN across pods, handled transparently by XLA's collective
lowering). There is no connection management, tag scheme, or bounce-buffer
pool to port: the compiler owns transport.
"""

from spark_rapids_tpu.parallel.mesh import (  # noqa: F401
    all_to_all_table,
    build_mesh,
    distributed_agg_step,
)
