"""One-time accelerator dispatch-latency probe.

The engine's sync-vs-stay-lazy tradeoffs (e.g. compacting partial-aggregate
output with a row-count round trip) depend on how expensive a host<->device
synchronization actually is.  On a locally attached chip a fence is
~0.1-1 ms and early compaction wins; on a tunneled/remote PJRT backend a
fence can cost tens of milliseconds, dwarfing any compute it saves.  The
reference hardcodes the cheap-sync assumption (CUDA streams on a local GPU);
a TPU-native engine instead measures once and lets policies adapt.

The probe runs two fenced round trips of a trivial jitted program on the
default backend and caches the minimum.  It must only be called from code
paths where the backend is already initialized (exec-layer policy hooks);
it never forces backend selection on its own.
"""

from __future__ import annotations

import os
import time
from typing import Optional

_fence_ms: Optional[float] = None


def fence_cost_ms() -> float:
    """Measured cost (ms) of one dispatch + blocking scalar readback on the
    default jax backend.  Cached for the process.  Override with
    ``SRT_FENCE_MS`` (float) for tests and benchmarks."""
    global _fence_ms
    if _fence_ms is not None:
        return _fence_ms
    env = os.environ.get("SRT_FENCE_MS")
    if env is not None:
        _fence_ms = float(env)
        return _fence_ms
    import jax
    import jax.numpy as jnp
    import numpy as np

    # tpulint: jit-cache -- one-shot probe; result memoized in _fence_ms
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    np.asarray(f(x))  # warm (compile)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        np.asarray(f(x))
        best = min(best, time.perf_counter() - t0)
    _fence_ms = best * 1e3
    return _fence_ms


def reset() -> None:
    """Test hook: forget the cached measurement."""
    global _fence_ms
    _fence_ms = None
