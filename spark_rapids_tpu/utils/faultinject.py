"""Deterministic, conf-gated fault-injection harness.

The substrate for the chaos suite (tests/test_faults.py): registered
execution sites consult `maybe_inject(site)` immediately before doing their
real work; when the harness is armed for that site, a seeded PRF decides
per invocation whether to raise the site's fault kind instead. Injection is
a PURE function of (seed, site, invocation count) — a run replays exactly
under the same seed, and every retry re-rolls with a fresh invocation count
so rates < 1 terminate (the CPU fallback backstops rate = 1).

Conf: rapids.tpu.test.faultInjection.{enabled,seed,sites,rate}
(disabled by default; `maybe_inject` is a single None-check when off).

Fault kinds and what they model:
- oom       XLA RESOURCE_EXHAUSTED on a device dispatch -> TpuRetryOOM
            (spill + re-dispatch, then split-and-retry, then CPU fallback)
- dispatch  a flaky program launch (XLA ABORTED) -> TpuTransientDeviceError
- transfer  a failed host<->device transfer -> TpuTransientDeviceError
- fetch     a lost shuffle piece -> FetchFailedError (upstream map
            partition re-execution, then task retry)
- delay     a straggler: the site sleeps faultInjection.delayMs (cancel-
            aware) then proceeds NORMALLY — no error raised; the self-
            healing layer (scheduler speculation) must hide the latency
- wedge     a hung dispatch: the site blocks until the watchdog
            (engine/watchdog.py) classifies it wedged, then raises a
            retryable TpuDispatchWedged (re-dispatch on fresh buffers)
- device_loss  the backend vanished (restart, ICI peer loss) ->
            TpuDeviceLostError; never retried in place — the session
            quarantines the device and replays/degrades (self-healing)

The reference grows the same substrate inside RMM for its retry tests
(RmmSpark.forceRetryOOM / forceSplitAndRetryOOM injecting OOMs at chosen
allocation counts); sites here are named execution points instead of
allocation indices because XLA owns allocation.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.utils import metrics as _M

# every registered site -> its default fault kind. Keep docs/fault-tolerance.md
# in sync when adding a site.
SITES: Dict[str, str] = {
    "scan": "oom",
    "project": "oom",
    "filter": "oom",
    "fused": "oom",
    "agg.update": "oom",
    "agg.merge": "oom",
    "agg.finalize": "oom",
    "join": "oom",
    "sort": "oom",
    "spmd.stage": "oom",
    "encoded.materialize": "oom",
    # the adaptive re-plan site (aqe/loop.py): a fault here must DEGRADE
    # the query to its original static plan shape, never change results
    "aqe.replan": "dispatch",
    "transfer.upload": "transfer",
    "transfer.download": "transfer",
    "shuffle.fetch": "fetch",
    # the cancellation-race site (engine/cancel.check_cancel): armed with
    # the "cancel" kind it fires a cancellation at one of the engine's
    # own poll points — a cancel racing engine progress. Excluded from
    # the '*' expansion: a cancelled query by design returns no rows, so
    # it can never be oracle-equal (arm it explicitly, chaos matrix in
    # tests/test_faults.py)
    "cancel.race": "cancel",
}

KINDS = ("oom", "dispatch", "transfer", "fetch", "cancel",
         "delay", "wedge", "device_loss")


# fault kinds that model a device COMPUTE failure: under async dispatch
# these surface at the sink download, not the issuing dispatch, so the
# deferToSink mode records them for sink-side re-raise (transfer/fetch
# faults happen in host-blocking operations and always raise in place)
_DEFERRABLE_KINDS = ("oom", "dispatch")
# the sink sites where a deferred fault surfaces (the engine's blocking
# device->host chokepoints)
SINK_SITES = ("transfer.download",)


class FaultInjector:
    """Armed sites + the seeded decision function."""

    def __init__(self, seed: int, sites_spec: str, rate: float,
                 defer_to_sink: bool = False, delay_ms: float = 400.0):
        self.seed = int(seed)
        self.rate = float(rate)
        self.defer_to_sink = bool(defer_to_sink)
        self.delay_ms = max(0.0, float(delay_ms))
        self.armed: Dict[str, str] = _parse_sites(sites_spec)
        self._lock = threading.Lock()
        self._invocations: Dict[str, int] = {}
        self._injected: Dict[str, int] = {}
        # (origin site, kind) faults recorded under deferToSink, waiting
        # to surface at the next sink download
        self._deferred: List[Tuple[str, str]] = []

    def decide(self, site: str, invocation: int) -> bool:
        """Pure (seed, site, invocation) -> inject? decision. crc32 keeps
        it stable across processes and python hash randomization."""
        h = zlib.crc32(f"{self.seed}:{site}:{invocation}".encode("utf-8"))
        return (h & 0xFFFFFFFF) / 4294967296.0 < self.rate

    def check(self, site: str) -> Optional[str]:
        """Count the invocation; return the fault kind to raise, or None."""
        kind = self.armed.get(site)
        if kind is None:
            return None
        with self._lock:
            n = self._invocations.get(site, 0)
            self._invocations[site] = n + 1
        if not self.decide(site, n):
            return None
        with self._lock:
            self._injected[site] = self._injected.get(site, 0) + 1
        return kind

    def injected_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)

    def invocation_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._invocations)

    def defer(self, site: str, kind: str) -> None:
        with self._lock:
            self._deferred.append((site, kind))

    def pop_deferred(self) -> Optional[Tuple[str, str]]:
        with self._lock:
            return self._deferred.pop(0) if self._deferred else None

    def deferred_pending(self) -> int:
        with self._lock:
            return len(self._deferred)

    def clear_deferred(self) -> None:
        with self._lock:
            self._deferred.clear()


def _parse_sites(spec: str) -> Dict[str, str]:
    """'*' or 'name[,name:kind,...]' -> {site: kind}. Unknown sites are
    accepted (tests register ad-hoc sites); unknown kinds raise."""
    armed: Dict[str, str] = {}
    spec = (spec or "").strip()
    if not spec:
        return armed
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if entry == "*":
            # everything EXCEPT cancel-kind sites: '*' arms the recover-
            # and-stay-oracle-equal chaos matrix, and a cancellation by
            # design produces no rows to compare — cancellation sites are
            # an explicit opt-in ('cancel.race' / 'site:cancel')
            armed.update({k: v for k, v in SITES.items()
                          if v != "cancel"})
            continue
        if ":" in entry:
            name, kind = entry.split(":", 1)
            name, kind = name.strip(), kind.strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} for site {name!r} "
                    f"(must be one of {'|'.join(KINDS)})")
        else:
            name = entry
            kind = SITES.get(name, "oom")
        armed[name] = kind
    return armed


_ACTIVE: Optional[FaultInjector] = None


def configure(tpu_conf: "C.TpuConf", ctx=None) -> Optional[FaultInjector]:
    """Arm (or disarm) the harness from a session conf; called at every
    query start so the executing session's conf is authoritative.

    With a QueryContext (multi-tenant serving, docs/serving.md) the
    injector is ADDITIONALLY scoped to that query: `active()` prefers the
    ambient context's injector, which contextvars propagation carries onto
    the query's worker threads — so one tenant arming injection cannot
    fault another tenant's concurrently running query. The process-global
    slot is still set (last writer wins) for direct callers outside any
    query context."""
    global _ACTIVE
    if not tpu_conf.get(C.FAULT_INJECTION_ENABLED):
        _ACTIVE = None
        if ctx is not None:
            ctx.injector = None
            ctx.fi_scoped = True
        return None
    inj = FaultInjector(
        seed=tpu_conf.get(C.FAULT_INJECTION_SEED),
        sites_spec=tpu_conf.get(C.FAULT_INJECTION_SITES),
        rate=tpu_conf.get(C.FAULT_INJECTION_RATE),
        defer_to_sink=tpu_conf.get(C.FAULT_INJECTION_DEFER_TO_SINK),
        delay_ms=tpu_conf.get(C.FAULT_INJECTION_DELAY_MS),
    )
    _ACTIVE = inj
    if ctx is not None:
        ctx.injector = inj
        ctx.fi_scoped = True
    return inj


def disable() -> None:
    """Disarm injection for the current scope: inside a query context the
    query's own injector clears (the fallback-run backstop must stay
    per-tenant); outside one, the process-global slot clears."""
    ctx = _M.current_query_ctx()
    if ctx is not None and ctx.fi_scoped:
        ctx.injector = None
        return
    global _ACTIVE
    _ACTIVE = None


def disable_global() -> None:
    """Unconditionally clear the process-global slot (session teardown)."""
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultInjector]:
    """The injector governing the calling thread: the ambient query
    context's when one is installed (per-tenant isolation), else the
    process-global slot."""
    ctx = _M.current_query_ctx()
    if ctx is not None and ctx.fi_scoped:
        return ctx.injector
    return _ACTIVE


def clear_deferred() -> None:
    """Drop any recorded-but-unsurfaced deferred faults (called before a
    checked replay: the replay re-executes from the start, and the first
    run's undelivered sink faults must not poison its downloads)."""
    inj = active()
    if inj is not None:
        inj.clear_deferred()


def raise_deferred_at_sink(site: str = "transfer.download") -> None:
    """Surface the oldest recorded deferred fault as a TpuAsyncSinkError
    naming its origin, or return. Called from `maybe_inject` at the sink
    sites — and by an EMPTY sink (session._sink_download with nothing to
    download), which still counts as the query's blocking point: a
    deferred fault must not vanish just because no rows survived."""
    inj = active()
    if inj is None:
        return
    pending = inj.pop_deferred()
    if pending is not None:
        origin, kind = pending
        from spark_rapids_tpu.engine.retry import TpuAsyncSinkError

        raise TpuAsyncSinkError(
            f"[injected] async device error surfaced at {site} "
            f"(origin: {kind} at {origin})", origin_site=origin)


def maybe_inject(site: str) -> None:
    """Raise the armed fault for `site`, or return. A single None-check
    when the harness is off — safe on every hot path.

    Under deferToSink (docs/async-execution.md) a device-COMPUTE fault
    (oom/dispatch kinds) is recorded instead of raised, and the next sink
    download (`transfer.download`) raises it as a TpuAsyncSinkError naming
    the originating site — modeling where a real async XLA error reaches
    the host. A checked replay (engine/async_exec.checked_mode) disables
    the deferral, so replayed faults raise at their sites."""
    inj = active()
    if inj is None:
        return
    if site in SINK_SITES:
        raise_deferred_at_sink(site)
    kind = inj.check(site)
    if kind is None:
        return
    if kind == "cancel":
        # a cancellation racing this site: fire the ambient query's token
        # (every later poll agrees) and raise the terminal error HERE —
        # never deferred, never retried (engine/cancel.py contract)
        from spark_rapids_tpu.engine.cancel import TpuQueryCancelled

        ctx = _M.current_query_ctx()
        if ctx is not None and ctx.cancel is not None:
            ctx.cancel.cancel(f"injected at {site}")
        raise TpuQueryCancelled(
            f"[injected] query cancelled racing {site}",
            reason=f"injected at {site}", site=site)
    if kind == "delay":
        # a straggler, not an error: sleep (cancel-aware — a deadline or
        # cancel still wins) and then let the site proceed normally. The
        # speculation layer's job is to make this latency invisible.
        from spark_rapids_tpu.engine.cancel import cancel_aware_sleep

        cancel_aware_sleep(inj.delay_ms / 1000.0, site=site)
        return
    if kind == "wedge":
        # a hung dispatch: block until the watchdog classifies this
        # attempt wedged, then raise the retryable TpuDispatchWedged
        from spark_rapids_tpu.engine.watchdog import simulate_wedge

        simulate_wedge(site)
        return
    if kind == "device_loss":
        from spark_rapids_tpu.engine.retry import TpuDeviceLostError

        raise TpuDeviceLostError(
            f"[injected] UNAVAILABLE: device lost at {site} "
            f"(backend restart / ICI peer loss)")
    if inj.defer_to_sink and kind in _DEFERRABLE_KINDS and \
            site not in SINK_SITES:
        from spark_rapids_tpu.engine.async_exec import async_enabled

        # deferral models ASYNC error timing: with issue-ahead off (or
        # inside a checked replay) dispatch is synchronous, so the fault
        # raises at its site where the per-op machinery owns it
        if async_enabled():
            inj.defer(site, kind)
            return
    # lazy imports: utils must not pull the engine in at module import
    from spark_rapids_tpu.engine.retry import (
        TpuRetryOOM,
        TpuTransientDeviceError,
    )

    if kind == "oom":
        raise TpuRetryOOM(
            f"[injected] RESOURCE_EXHAUSTED: out of memory at {site}")
    if kind == "dispatch":
        raise TpuTransientDeviceError(
            f"[injected] ABORTED: device dispatch failed at {site}")
    if kind == "transfer":
        raise TpuTransientDeviceError(
            f"[injected] UNAVAILABLE: host<->device transfer failed "
            f"at {site}")
    from spark_rapids_tpu.engine.scheduler import FetchFailedError

    raise FetchFailedError(f"[injected] shuffle piece lost at {site}")
