"""Host-process environment control for backend selection.

The deployment environment may export accelerator-runtime variables (e.g.
the axon PJRT plugin's pool/remote-compile settings) that force jax onto
the real chip even when a CPU-backend virtual mesh is wanted — and its
sitecustomize forces the TPU backend regardless of ``JAX_PLATFORMS`` while
``PALLAS_AXON_POOL_IPS`` is set.  Every place that needs a scrubbed
CPU-backend child environment (bench supervisor, multichip dryrun, test
conftest) must share ONE scrub rule set so a newly discovered variable is
removed everywhere at once.

Imports nothing heavier than ``os`` — safe for supervisors that must not
touch jax themselves.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

# Env vars that, when present, let the accelerator runtime hijack backend
# selection away from the CPU host platform.
_ACCELERATOR_ENV_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE",
)


def scrubbed_cpu_env(n_devices: Optional[int] = None,
                     base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Return a copy of ``base`` (default ``os.environ``) forcing the jax
    CPU backend, optionally with ``n_devices`` virtual host devices.

    Must be applied to a child process (or to ``os.environ`` before jax
    initializes a backend) — backend choice is latched at first init.
    """
    env = dict(os.environ if base is None else base)
    for var in _ACCELERATOR_ENV_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f)
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env


def apply_cpu_env(n_devices: Optional[int] = None) -> None:
    """In-place variant for processes that have not yet initialized jax."""
    os.environ.update(scrubbed_cpu_env(n_devices))
    for var in _ACCELERATOR_ENV_VARS:
        os.environ.pop(var, None)


def ensure_cpu_env(default_devices: int = 8) -> None:
    """Force the scrubbed CPU env in-place, adding ``default_devices``
    virtual host devices unless the caller's ``XLA_FLAGS`` already pins a
    device count. The ONE entry-point rule shared by the test conftest
    and the standalone distributed tests, so the device-count handling
    cannot diverge between the pytest and standalone paths."""
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        apply_cpu_env(default_devices)
    else:
        apply_cpu_env()
