"""Metrics + tracing.

Reference parity:
- GpuMetricNames / GpuExec standard metrics (GpuExec.scala:24-41): numOutputRows,
  numOutputBatches, totalTime, peakDevMemory, plus op-specific metrics.
- NvtxWithMetrics (NvtxWithMetrics.scala:27-44): a profiler range that adds its
  elapsed time to a metric on close. The TPU analog is
  jax.profiler.TraceAnnotation (XProf/TraceMe), falling back to a no-op
  timer when the profiler is unavailable.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

try:
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover
    _TraceAnnotation = None

# standard metric names (reference: GpuMetricNames, GpuExec.scala:24-41)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
TOTAL_TIME = "totalTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
# whole-stage fusion metrics (plan/fusion.py; Spark's WholeStageCodegen has
# no dispatch analog — on an accelerator every program launch is one host
# round trip, so the dispatch count IS the fusion win's unit)
FUSED_STAGES = "fusedStages"
DEVICE_DISPATCHES = "deviceDispatches"
# fault-tolerance metrics (engine/retry.py; reference: the retry/OOM state
# machine the plugin wraps every GPU allocation in + per-op CPU fallback)
RETRIES = "retries"
SPLIT_RETRIES = "splitRetries"
CPU_FALLBACK_EVENTS = "cpuFallbackEvents"
FETCH_RETRIES = "fetchRetries"
# async issue-ahead metrics (engine/async_exec.py, docs/async-execution.md):
# fences = device->host transfer events the engine issued (the
# site="transfer.download" instrumentation); checkedReplays = whole-query
# re-executions in checked (synchronous) mode after an error surfaced at
# the sink; donatedBytes = input bytes donated into consume-once kernels
FENCES = "fencesPerQuery"
CHECKED_REPLAYS = "checkedReplays"
DONATED_BYTES = "donatedBytes"
# single-program SPMD stage metrics (plan/spmd.py, engine/spmd_exec.py):
# spmdStages = stage pipelines that executed as ONE shard_map program over
# the mesh; collectiveBytes = bytes moved by in-program ICI collectives
# (the all_to_all exchange epoch and the sort-absorbing all_gather)
SPMD_STAGES = "spmdStages"
COLLECTIVE_BYTES = "collectiveBytes"


class Metric:
    """A thread-safe accumulator (the SQLMetric analog)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, v) -> None:
        with self._lock:
            self._value += v

    def set_max(self, v) -> None:
        with self._lock:
            self._value = max(self._value, v)

    @property
    def value(self):
        return self._value


class MetricsMap:
    """Per-exec metric registry."""

    def __init__(self, *names: str):
        self._metrics: Dict[str, Metric] = {}
        for n in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, TOTAL_TIME,
                  PEAK_DEVICE_MEMORY) + names:
            self._metrics[n] = Metric(n)

    def __getitem__(self, name: str) -> Metric:
        if name not in self._metrics:
            self._metrics[name] = Metric(name)
        return self._metrics[name]

    def snapshot(self) -> Dict[str, int]:
        return {k: m.value for k, m in self._metrics.items()}


# ---------------------------------------------------------------------------
# Device-dispatch accounting
# ---------------------------------------------------------------------------
# Process-wide: partition tasks run on a shared worker pool, so per-exec
# counters would need threading context; queries snapshot before/after
# instead (session.execute_batches -> session.last_query_metrics).
_DISPATCHES = Metric(DEVICE_DISPATCHES)

# measurement hook invoked after every record_dispatch (None = disabled).
# Used by TpuDeviceManager's live-bytes peak sampler: dispatches are the
# engine's natural "device state changed" cadence, so sampling here catches
# the high-water mark without instrumenting every allocation site.
_DISPATCH_HOOK = None


def set_dispatch_hook(fn) -> None:
    """Install (or clear, with None) the post-dispatch measurement hook.
    The hook runs on the dispatching thread with no arguments; keep it
    cheap — it fires on every device dispatch while installed."""
    global _DISPATCH_HOOK
    _DISPATCH_HOOK = fn


def record_dispatch(n: int = 1) -> None:
    """Count a device program launch (jitted kernel invocation). Called at
    the engine's kernel entry points — projector/filter/fused-stage/agg
    kernels and the batch gather/compact helpers — NOT per XLA executable
    internals; the unit is 'host->device dispatches the engine issued'."""
    _DISPATCHES.add(n)
    hook = _DISPATCH_HOOK
    if hook is not None:
        hook()


def dispatch_count() -> int:
    return _DISPATCHES.value


# ---------------------------------------------------------------------------
# Fault-tolerance accounting (engine/retry.py increments; queries snapshot
# before/after, same pattern as the dispatch counter above)
# ---------------------------------------------------------------------------
_RETRIES = Metric(RETRIES)
_SPLIT_RETRIES = Metric(SPLIT_RETRIES)
_CPU_FALLBACKS = Metric(CPU_FALLBACK_EVENTS)
_FETCH_RETRIES = Metric(FETCH_RETRIES)
_FENCES = Metric(FENCES)
_CHECKED_REPLAYS = Metric(CHECKED_REPLAYS)
_DONATED_BYTES = Metric(DONATED_BYTES)
_SPMD_STAGES = Metric(SPMD_STAGES)
_COLLECTIVE_BYTES = Metric(COLLECTIVE_BYTES)


def record_retry(n: int = 1) -> None:
    """Count one device re-dispatch (OOM spill+retry or transient retry)."""
    _RETRIES.add(n)


def record_split_retry(n: int = 1) -> None:
    """Count one batch bisection performed by split-and-retry."""
    _SPLIT_RETRIES.add(n)


def record_cpu_fallback(n: int = 1) -> None:
    """Count one degradation to the CPU-oracle path (per batch or per
    query, whichever unit fell back)."""
    _CPU_FALLBACKS.add(n)


def record_fetch_retry(n: int = 1) -> None:
    """Count one shuffle-piece re-execution after a fetch failure."""
    _FETCH_RETRIES.add(n)


def retry_count() -> int:
    return _RETRIES.value


def split_retry_count() -> int:
    return _SPLIT_RETRIES.value


def cpu_fallback_count() -> int:
    return _CPU_FALLBACKS.value


def fetch_retry_count() -> int:
    return _FETCH_RETRIES.value


def record_fence(n: int = 1) -> None:
    """Count one device->host transfer event (a host fence). The engine's
    download chokepoints record here: with_retry(site='transfer.download')
    sink downloads and the shuffle's grouped piece encodes — NOT internal
    flush granularity, so the unit is 'download transfers the engine
    issued' (the ~66 ms round trip on a tunneled backend)."""
    _FENCES.add(n)


def fence_count() -> int:
    return _FENCES.value


def record_checked_replay(n: int = 1) -> None:
    """Count one whole-query checked-mode re-execution (a device error
    surfaced at the sink under async dispatch / donation; the session
    replays synchronously so the originating op's retry machinery can
    own it)."""
    _CHECKED_REPLAYS.add(n)


def checked_replay_count() -> int:
    return _CHECKED_REPLAYS.value


def record_donated_bytes(n: int) -> None:
    """Count input bytes donated into a consume-once kernel (the HBM the
    output reused instead of allocating fresh)."""
    _DONATED_BYTES.add(n)


def donated_bytes() -> int:
    return _DONATED_BYTES.value


def record_spmd_stage(n: int = 1) -> None:
    """Count one stage pipeline executed as a single SPMD program over the
    mesh (operators AND exchange compiled into one dispatch)."""
    _SPMD_STAGES.add(n)


def spmd_stage_count() -> int:
    return _SPMD_STAGES.value


def record_collective_bytes(n: int) -> None:
    """Count bytes moved by an in-program ICI collective (the all_to_all
    exchange epoch of an SPMD stage or the standalone ICI shuffle tier,
    and the sort-absorbing all_gather)."""
    _COLLECTIVE_BYTES.add(n)


def collective_bytes() -> int:
    return _COLLECTIVE_BYTES.value


@contextlib.contextmanager
def trace_range(name: str, metric: Optional[Metric] = None):
    """NvtxWithMetrics analog: XProf trace annotation + elapsed-ns metric."""
    start = time.perf_counter_ns()
    if _TraceAnnotation is not None:
        cm = _TraceAnnotation(name)
    else:  # pragma: no cover
        cm = contextlib.nullcontext()
    with cm:
        try:
            yield
        finally:
            if metric is not None:
                metric.add(time.perf_counter_ns() - start)
