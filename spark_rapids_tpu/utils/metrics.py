"""Metrics + tracing.

Reference parity:
- GpuMetricNames / GpuExec standard metrics (GpuExec.scala:24-41): numOutputRows,
  numOutputBatches, totalTime, peakDevMemory, plus op-specific metrics.
- NvtxWithMetrics (NvtxWithMetrics.scala:27-44): a profiler range that adds its
  elapsed time to a metric on close. The TPU analog is
  jax.profiler.TraceAnnotation (XProf/TraceMe), falling back to a no-op
  timer when the profiler is unavailable.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Dict, Optional

try:
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover
    _TraceAnnotation = None

# standard metric names (reference: GpuMetricNames, GpuExec.scala:24-41)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
TOTAL_TIME = "totalTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
# whole-stage fusion metrics (plan/fusion.py; Spark's WholeStageCodegen has
# no dispatch analog — on an accelerator every program launch is one host
# round trip, so the dispatch count IS the fusion win's unit)
FUSED_STAGES = "fusedStages"
DEVICE_DISPATCHES = "deviceDispatches"
# fault-tolerance metrics (engine/retry.py; reference: the retry/OOM state
# machine the plugin wraps every GPU allocation in + per-op CPU fallback)
RETRIES = "retries"
SPLIT_RETRIES = "splitRetries"
CPU_FALLBACK_EVENTS = "cpuFallbackEvents"
FETCH_RETRIES = "fetchRetries"
# async issue-ahead metrics (engine/async_exec.py, docs/async-execution.md):
# fences = device->host transfer events the engine issued (the
# site="transfer.download" instrumentation); checkedReplays = whole-query
# re-executions in checked (synchronous) mode after an error surfaced at
# the sink; donatedBytes = input bytes donated into consume-once kernels
FENCES = "fencesPerQuery"
CHECKED_REPLAYS = "checkedReplays"
DONATED_BYTES = "donatedBytes"
# single-program SPMD stage metrics (plan/spmd.py, engine/spmd_exec.py):
# spmdStages = stage pipelines that executed as ONE shard_map program over
# the mesh; collectiveBytes = bytes moved by in-program ICI collectives
# (the all_to_all exchange epoch and the sort-absorbing all_gather)
SPMD_STAGES = "spmdStages"
COLLECTIVE_BYTES = "collectiveBytes"
# serving-runtime metrics (plan/plan_cache.py, engine/admission.py,
# engine/server.py, docs/serving.md): planCacheHits/Misses count
# signature-cache lookups for cache-enabled queries (a hit skips planning,
# verification, AND resource analysis); admissionWaits counts queries that
# blocked in analyzer-driven HBM admission before running;
# microBatches/microBatchedQueries count packed windows and the individual
# queries that rode in one
PLAN_CACHE_HITS = "planCacheHits"
PLAN_CACHE_MISSES = "planCacheMisses"
ADMISSION_WAITS = "admissionWaits"
# admissionWaits counts EVENTS; this accumulates the waited DURATION in
# nanoseconds (engine/admission.py measures it via the obs wall clock) —
# the server snapshot additionally surfaces a p50/p95 from the
# controller's bounded sample reservoir
ADMISSION_WAIT_NS = "admissionWaitNs"
MICRO_BATCHES = "microBatches"
MICRO_BATCHED_QUERIES = "microBatchedQueries"
# encoded columnar execution (columnar/encoded.py,
# docs/compressed-execution.md): encodedColumns counts device columns the
# scan layer emitted ENCODED (codes + shared dictionary, per column per
# decoded chunk); lateMaterializations counts explicit decode events — the
# only path from codes back to values (device materialize() at an operator
# boundary, host expansion at the result sink / serde); encodedBytesSaved
# accumulates the HBM the encoded representation avoided at scan emission,
# rows x (string-estimate bytes - code bytes) per encoded column — the
# same formula the resource analyzer predicts, so containment is testable
ENCODED_COLUMNS = "encodedColumns"
LATE_MATERIALIZATIONS = "lateMaterializations"
ENCODED_BYTES_SAVED = "encodedBytesSaved"
# order-preserving / run-aware compressed compute (PR: rank-space sorts):
# orderPreservingSorts counts sorts / range-bound computations / window
# orderings that ran over rank codes instead of decoding (one count per
# batch kept in rank space); runCollapsedRows accumulates rows the
# run-granular aggregate path collapsed away (rows - runs per collapsed
# update batch)
ORDER_PRESERVING_SORTS = "orderPreservingSorts"
RUN_COLLAPSED_ROWS = "runCollapsedRows"
# adaptive query execution (spark_rapids_tpu/aqe/,
# docs/adaptive-execution.md): aqeReplans counts rule applications that
# rewrote (and statically re-validated) the not-yet-executed remainder;
# skewSplits counts oversized reduce buckets split into sub-partitions;
# joinDemotions/joinPromotions count runtime join-strategy switches
# (shuffled -> broadcast / broadcast -> shuffled)
# single-program SPMD composition (plan/spmd.py, engine/spmd_exec.py):
# spmdJoins counts INNER equi-joins lowered INTO a stage program (build
# broadcast via in-program all_gather); spmdMeasuredCaps counts stage
# segments whose exchange-bucket capacity came from AQE's MEASURED
# MapOutputStats instead of the analyzer's pessimistic interval
SPMD_JOINS = "spmdJoins"
SPMD_MEASURED_CAPS = "spmdMeasuredCaps"
AQE_REPLANS = "aqeReplans"
SKEW_SPLITS = "skewSplits"
JOIN_DEMOTIONS = "joinDemotions"
JOIN_PROMOTIONS = "joinPromotions"
# cooperative cancellation / deadline / overload shedding
# (engine/cancel.py, engine/admission.py, docs/fault-tolerance.md):
# cancelledQueries counts queries that raised TpuQueryCancelled
# (explicit cancel, drain, or a MID-FLIGHT deadline expiry);
# deadlineRejects counts queries rejected BEFORE execution because the
# deadline was already spent or the predicted work could not fit the
# remaining budget (zero device dispatches by construction); shedQueries
# counts queries the overload policy refused (bounded admission queue
# depth / max queue wait / draining server)
CANCELLED_QUERIES = "cancelledQueries"
DEADLINE_REJECTS = "deadlineRejects"
SHED_QUERIES = "shedQueries"
# cost-based placement (plan/placement.py, docs/placement.md):
# hostPlacedOps counts operators the placement analyzer moved host-side
# in the emitted plan; placementReplacements counts re-placements after
# the fact (an AQE re-place on measured stats, or a device failure
# re-placed onto the host instead of a whole-query CPU fallback)
HOST_PLACED_OPS = "hostPlacedOps"
PLACEMENT_REPLACEMENTS = "placementReplacements"
# self-healing execution (engine/scheduler.py speculation,
# engine/watchdog.py, memory/device_manager.py quarantine;
# docs/fault-tolerance.md): speculativeTasks counts straggler duplicates
# launched, speculativeWins the duplicates that finished first;
# watchdogKills counts in-flight dispatches the watchdog classified
# wedged (released for retry or escalated to a query kill); deviceResets
# counts device-loss events that quarantined a device
SPECULATIVE_TASKS = "speculativeTasks"
SPECULATIVE_WINS = "speculativeWins"
WATCHDOG_KILLS = "watchdogKills"
DEVICE_RESETS = "deviceResets"


class Metric:
    """A thread-safe accumulator (the SQLMetric analog)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, v) -> None:
        with self._lock:
            self._value += v

    def set_max(self, v) -> None:
        with self._lock:
            self._value = max(self._value, v)

    @property
    def value(self):
        return self._value


class MetricsMap:
    """Per-exec metric registry."""

    def __init__(self, *names: str):
        self._metrics: Dict[str, Metric] = {}
        for n in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, TOTAL_TIME,
                  PEAK_DEVICE_MEMORY) + names:
            self._metrics[n] = Metric(n)

    def __getitem__(self, name: str) -> Metric:
        if name not in self._metrics:
            self._metrics[name] = Metric(name)
        return self._metrics[name]

    def snapshot(self) -> Dict[str, int]:
        return {k: m.value for k, m in self._metrics.items()}


# ---------------------------------------------------------------------------
# Per-query / per-tenant accumulation context
# ---------------------------------------------------------------------------
# Before the serving runtime, per-query metrics were before/after snapshots
# of the process-wide counters — which cross-talk the moment two queries
# run concurrently. A QueryContext is installed by the session around each
# query (a contextvar, propagated onto scheduler worker threads and the
# prefetch reader by contextvars.copy_context), and every record_* helper
# accumulates into BOTH the global counter (bench/tools keep reading those)
# and the ambient query's context. The context also carries the per-tenant
# policy objects that used to be process singletons: the tenant's circuit
# breaker, the query's fault injector, the per-query retry budget, and the
# analyzer's semaphore admission weight.
_QUERY_CTX: "contextvars.ContextVar[Optional[QueryContext]]" = \
    contextvars.ContextVar("srt_query_ctx", default=None)


class QueryContext:
    """One running query's metric accumulator + per-tenant policy handles
    (docs/serving.md). Thread-safe: partition tasks on the worker pool add
    concurrently."""

    __slots__ = ("tenant", "_lock", "_counters", "breaker", "injector",
                 "fi_scoped", "retry_budget", "_retries_spent", "sem_weight",
                 "resource_report", "retry_policy", "aqe_notes",
                 "spill_plan_hint", "async_dispatch", "donation", "trace",
                 "cancel", "spill_buffers", "prefetchers", "kill_reason",
                 "placement_payload", "predicted_work_ns")

    def __init__(self, tenant: str = "default"):
        self.tenant = tenant
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        # per-tenant circuit breaker (engine/retry.CircuitBreaker.get
        # consults this before the process default)
        self.breaker = None
        # per-query fault injector; fi_scoped=True means the injector slot
        # is authoritative for this query even when it is None (the query
        # ran with injection off while another tenant's is armed)
        self.injector = None
        self.fi_scoped = False
        # per-query task-retry budget (0 = unlimited); the scheduler's
        # _try_spend_retry charges here when a context is ambient, so
        # concurrent queries cannot drain each other's budget
        self.retry_budget = 0
        self._retries_spent = 0
        # semaphore permits one task of this query holds (the analyzer's
        # admission weight, read by TpuSemaphore.acquire_if_necessary)
        self.sem_weight = 1
        # THIS query's resource-analyzer report (set during planning —
        # including from a plan-cache hit); the admission controller reads
        # it here so concurrent queries on one session cannot read each
        # other's via the session attribute
        self.resource_report = None
        # per-query retry policy (engine/retry.set_policy_from_conf):
        # combinators read policy() through the ambient context, so one
        # tenant's backoff/retry tuning never leaks into another's
        # concurrently running query
        self.retry_policy = None
        # adaptive-execution notes (aqe/loop.py): applied-rule lines the
        # session surfaces as last_adaptive_report / EXPLAIN's
        # '== Adaptive execution ==' section
        self.aqe_notes = []
        # context-scoped spill plan reserve (memory/spill.py): resolved
        # reserve bytes for THIS query's predicted transients. None = no
        # hint posted yet (the watermark falls back to its process-wide
        # slot); an AQE re-plan posting a new hint lands here, so it can
        # never leak into a concurrent tenant's query
        self.spill_plan_hint = None
        # context-scoped issue-ahead flags (engine/async_exec.py): the
        # executing session's asyncDispatch/bufferDonation resolution for
        # THIS query. None = fall back to the process-wide flags
        self.async_dispatch = None
        self.donation = None
        # THIS query's span tracer (obs/trace.QueryTracer; None = tracing
        # off, the zero-cost default). Installed by the session when
        # rapids.tpu.obs.tracing.enabled; every record_* chokepoint
        # mirrors its increment onto the tracer's current span via _note,
        # so the timeline shows WHERE dispatches/retries/fences happened
        self.trace = None
        # THIS query's cancellation token (engine/cancel.CancelToken;
        # None outside session-driven queries). Installed by the session
        # at query start and polled at every engine chokepoint —
        # contextvars propagation carries it onto worker threads and the
        # prefetch reader exactly like the context itself.
        self.cancel = None
        # spill-store buffers registered on behalf of THIS query
        # (memory/spill.py add_* with scope_to_query): the reclamation
        # set a cancellation frees so a dead query's shuffle pieces and
        # staged batches cannot linger in the store
        self.spill_buffers = []
        # live PrefetchIterators decoding for THIS query (io/prefetch.py
        # registers them): cancellation closes them and joins their
        # reader threads (bounded) so no thread outlives the query
        self.prefetchers = []
        # terminal-status tag for the flight recorder (obs/history.py):
        # session._on_query_killed stamps "cancelled"/"deadline"/"shed"
        # so the persisted history record carries how the query ended
        self.kill_reason = None
        # THIS query's placement decision (plan/placement.py
        # PlacementReport.to_payload()): the flight recorder persists it
        # and scores placementRegret against the measured wall
        self.placement_payload = None
        # the admission-time cost-model prediction of THIS query's device
        # work in ns (0 = no prediction): the scheduler's straggler
        # speculation and the watchdog's calibrated timeout divide it by
        # the job's task count to price one task's expected wall
        self.predicted_work_ns = 0

    def add(self, name: str, n: int) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def value(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # -- retry budget (engine/scheduler.py charges here) ---------------------
    def begin_retry_budget(self, budget: int) -> None:
        with self._lock:
            self.retry_budget = max(0, int(budget))
            self._retries_spent = 0

    def try_spend_retry(self) -> bool:
        with self._lock:
            if self.retry_budget and \
                    self._retries_spent >= self.retry_budget:
                return False
            self._retries_spent += 1
            return True

    @property
    def retries_spent(self) -> int:
        with self._lock:
            return self._retries_spent


def current_query_ctx() -> Optional[QueryContext]:
    return _QUERY_CTX.get()


def push_query_ctx(ctx: Optional[QueryContext]):
    """Install `ctx` as the ambient query context; returns the reset token
    for pop_query_ctx."""
    return _QUERY_CTX.set(ctx)


def pop_query_ctx(token) -> None:
    _QUERY_CTX.reset(token)


def _note(name: str, n: int) -> None:
    """Mirror a global-counter increment into the ambient query context —
    and, when the query is traced, onto the tracer's current span (one
    attribute check when tracing is off: the zero-cost contract of
    docs/observability.md)."""
    ctx = _QUERY_CTX.get()
    if ctx is not None:
        ctx.add(name, n)
        tr = ctx.trace
        if tr is not None:
            tr.add_count(name, n)


# ---------------------------------------------------------------------------
# Device-dispatch accounting
# ---------------------------------------------------------------------------
# Process-wide: partition tasks run on a shared worker pool, so per-exec
# counters would need threading context; queries ALSO accumulate into the
# ambient QueryContext (session.execute_batches ->
# session.last_query_metrics), which is what keeps concurrent tenants'
# numbers apart.
_DISPATCHES = Metric(DEVICE_DISPATCHES)

# measurement hook invoked after every record_dispatch (None = disabled).
# Used by TpuDeviceManager's live-bytes peak sampler: dispatches are the
# engine's natural "device state changed" cadence, so sampling here catches
# the high-water mark without instrumenting every allocation site.
_DISPATCH_HOOK = None


def set_dispatch_hook(fn) -> None:
    """Install (or clear, with None) the post-dispatch measurement hook.
    The hook runs on the dispatching thread with no arguments; keep it
    cheap — it fires on every device dispatch while installed."""
    global _DISPATCH_HOOK
    _DISPATCH_HOOK = fn


def record_dispatch(n: int = 1) -> None:
    """Count a device program launch (jitted kernel invocation). Called at
    the engine's kernel entry points — projector/filter/fused-stage/agg
    kernels and the batch gather/compact helpers — NOT per XLA executable
    internals; the unit is 'host->device dispatches the engine issued'."""
    _DISPATCHES.add(n)
    _note(DEVICE_DISPATCHES, n)
    hook = _DISPATCH_HOOK
    if hook is not None:
        hook()


def dispatch_count() -> int:
    return _DISPATCHES.value


# ---------------------------------------------------------------------------
# Fault-tolerance accounting (engine/retry.py increments; queries snapshot
# before/after, same pattern as the dispatch counter above)
# ---------------------------------------------------------------------------
_RETRIES = Metric(RETRIES)
_SPLIT_RETRIES = Metric(SPLIT_RETRIES)
_CPU_FALLBACKS = Metric(CPU_FALLBACK_EVENTS)
_FETCH_RETRIES = Metric(FETCH_RETRIES)
_FENCES = Metric(FENCES)
_CHECKED_REPLAYS = Metric(CHECKED_REPLAYS)
_DONATED_BYTES = Metric(DONATED_BYTES)
_SPMD_STAGES = Metric(SPMD_STAGES)
_COLLECTIVE_BYTES = Metric(COLLECTIVE_BYTES)


def record_retry(n: int = 1) -> None:
    """Count one device re-dispatch (OOM spill+retry or transient retry)."""
    _RETRIES.add(n)
    _note(RETRIES, n)


def record_split_retry(n: int = 1) -> None:
    """Count one batch bisection performed by split-and-retry."""
    _SPLIT_RETRIES.add(n)
    _note(SPLIT_RETRIES, n)


def record_cpu_fallback(n: int = 1) -> None:
    """Count one degradation to the CPU-oracle path (per batch or per
    query, whichever unit fell back)."""
    _CPU_FALLBACKS.add(n)
    _note(CPU_FALLBACK_EVENTS, n)


def record_fetch_retry(n: int = 1) -> None:
    """Count one shuffle-piece re-execution after a fetch failure."""
    _FETCH_RETRIES.add(n)
    _note(FETCH_RETRIES, n)


def retry_count() -> int:
    return _RETRIES.value


def split_retry_count() -> int:
    return _SPLIT_RETRIES.value


def cpu_fallback_count() -> int:
    return _CPU_FALLBACKS.value


def fetch_retry_count() -> int:
    return _FETCH_RETRIES.value


def record_fence(n: int = 1) -> None:
    """Count one device->host transfer event (a host fence). The engine's
    download chokepoints record here: with_retry(site='transfer.download')
    sink downloads and the shuffle's grouped piece encodes — NOT internal
    flush granularity, so the unit is 'download transfers the engine
    issued' (the ~66 ms round trip on a tunneled backend)."""
    _FENCES.add(n)
    _note(FENCES, n)


def fence_count() -> int:
    return _FENCES.value


def record_checked_replay(n: int = 1) -> None:
    """Count one whole-query checked-mode re-execution (a device error
    surfaced at the sink under async dispatch / donation; the session
    replays synchronously so the originating op's retry machinery can
    own it)."""
    _CHECKED_REPLAYS.add(n)
    _note(CHECKED_REPLAYS, n)


def checked_replay_count() -> int:
    return _CHECKED_REPLAYS.value


def record_donated_bytes(n: int) -> None:
    """Count input bytes donated into a consume-once kernel (the HBM the
    output reused instead of allocating fresh)."""
    _DONATED_BYTES.add(n)
    _note(DONATED_BYTES, n)


def donated_bytes() -> int:
    return _DONATED_BYTES.value


def record_spmd_stage(n: int = 1) -> None:
    """Count one stage pipeline executed as a single SPMD program over the
    mesh (operators AND exchange compiled into one dispatch)."""
    _SPMD_STAGES.add(n)
    _note(SPMD_STAGES, n)


def spmd_stage_count() -> int:
    return _SPMD_STAGES.value


def record_collective_bytes(n: int) -> None:
    """Count bytes moved by an in-program ICI collective (the all_to_all
    exchange epoch of an SPMD stage or the standalone ICI shuffle tier,
    and the sort-absorbing all_gather)."""
    _COLLECTIVE_BYTES.add(n)
    _note(COLLECTIVE_BYTES, n)


def collective_bytes() -> int:
    return _COLLECTIVE_BYTES.value


_SPMD_JOINS = Metric(SPMD_JOINS)
_SPMD_MEASURED_CAPS = Metric(SPMD_MEASURED_CAPS)


def record_spmd_join(n: int = 1) -> None:
    """Count one INNER equi-join lowered into an SPMD stage program (the
    build side broadcast in-program via lax.all_gather)."""
    _SPMD_JOINS.add(n)
    _note(SPMD_JOINS, n)


def spmd_join_count() -> int:
    return _SPMD_JOINS.value


def record_spmd_measured_cap(n: int = 1) -> None:
    """Count one SPMD stage segment whose capacities came from AQE's
    MEASURED MapOutputStats instead of the analyzer's interval."""
    _SPMD_MEASURED_CAPS.add(n)
    _note(SPMD_MEASURED_CAPS, n)


def spmd_measured_cap_count() -> int:
    return _SPMD_MEASURED_CAPS.value


# ---------------------------------------------------------------------------
# Serving-runtime accounting (plan cache / admission / micro-batching)
# ---------------------------------------------------------------------------
_PLAN_CACHE_HITS = Metric(PLAN_CACHE_HITS)
_PLAN_CACHE_MISSES = Metric(PLAN_CACHE_MISSES)
_ADMISSION_WAITS = Metric(ADMISSION_WAITS)
_ADMISSION_WAIT_NS = Metric(ADMISSION_WAIT_NS)
_MICRO_BATCHES = Metric(MICRO_BATCHES)
_MICRO_BATCHED_QUERIES = Metric(MICRO_BATCHED_QUERIES)


def record_plan_cache_hit(n: int = 1) -> None:
    """Count one signature-cache hit: the query reused a fully planned,
    verified, and analyzed physical plan — zero planning work (and, via
    the shared expression objects, zero retracing in the jit cache)."""
    _PLAN_CACHE_HITS.add(n)
    _note(PLAN_CACHE_HITS, n)


def plan_cache_hit_count() -> int:
    return _PLAN_CACHE_HITS.value


def record_plan_cache_miss(n: int = 1) -> None:
    """Count one signature-cache miss (the query planned from scratch and
    seeded the cache). Only cache-enabled, cacheable queries count."""
    _PLAN_CACHE_MISSES.add(n)
    _note(PLAN_CACHE_MISSES, n)


def plan_cache_miss_count() -> int:
    return _PLAN_CACHE_MISSES.value


def record_admission_wait(n: int = 1) -> None:
    """Count one query that blocked in analyzer-driven HBM admission
    (engine/admission.py) before it could start executing."""
    _ADMISSION_WAITS.add(n)
    _note(ADMISSION_WAITS, n)


def admission_wait_count() -> int:
    return _ADMISSION_WAITS.value


def record_admission_wait_ns(n: int) -> None:
    """Accumulate the DURATION one query spent blocked in analyzer-driven
    admission (ns; the admissionWaits event counter's missing half —
    engine/admission.py measures it with the obs wall clock)."""
    _ADMISSION_WAIT_NS.add(n)
    _note(ADMISSION_WAIT_NS, n)


def admission_wait_ns() -> int:
    return _ADMISSION_WAIT_NS.value


def record_micro_batch(n: int = 1) -> None:
    """Count one packed micro-batch window executed as a single query."""
    _MICRO_BATCHES.add(n)
    _note(MICRO_BATCHES, n)


def micro_batch_count() -> int:
    return _MICRO_BATCHES.value


def record_micro_batched_query(n: int = 1) -> None:
    """Count one individual query that rode in a packed micro-batch."""
    _MICRO_BATCHED_QUERIES.add(n)
    _note(MICRO_BATCHED_QUERIES, n)


def micro_batched_query_count() -> int:
    return _MICRO_BATCHED_QUERIES.value


# ---------------------------------------------------------------------------
# Encoded columnar execution accounting (columnar/encoded.py)
# ---------------------------------------------------------------------------
_ENCODED_COLUMNS = Metric(ENCODED_COLUMNS)
_LATE_MATERIALIZATIONS = Metric(LATE_MATERIALIZATIONS)
_ENCODED_BYTES_SAVED = Metric(ENCODED_BYTES_SAVED)


def record_encoded_column(n: int = 1) -> None:
    """Count one device column emitted ENCODED by the scan layer (codes in
    HBM + shared dictionary; one count per column per decoded chunk)."""
    _ENCODED_COLUMNS.add(n)
    _note(ENCODED_COLUMNS, n)


def encoded_column_count() -> int:
    return _ENCODED_COLUMNS.value


def record_late_materialization(n: int = 1) -> None:
    """Count one explicit decode of an encoded column back to values —
    the materialize() boundary path or the sink/serde host expansion. The
    compressed-execution contract is that this never happens silently
    (tpulint rule eager-materialize)."""
    _LATE_MATERIALIZATIONS.add(n)
    _note(LATE_MATERIALIZATIONS, n)


def late_materialization_count() -> int:
    return _LATE_MATERIALIZATIONS.value


def record_encoded_bytes_saved(n: int) -> None:
    """Accumulate HBM bytes the encoded representation avoided at scan
    emission: rows x (string per-row estimate - encoded per-row bytes),
    the deterministic formula the resource analyzer predicts an interval
    for (containment pinned by tests)."""
    _ENCODED_BYTES_SAVED.add(n)
    _note(ENCODED_BYTES_SAVED, n)


def encoded_bytes_saved() -> int:
    return _ENCODED_BYTES_SAVED.value


_ORDER_PRESERVING_SORTS = Metric(ORDER_PRESERVING_SORTS)
_RUN_COLLAPSED_ROWS = Metric(RUN_COLLAPSED_ROWS)


def record_order_preserving_sort(n: int = 1) -> None:
    """Count one batch whose sort / range-bound / window ordering ran
    over order-preserving rank codes instead of decoding the column."""
    _ORDER_PRESERVING_SORTS.add(n)
    _note(ORDER_PRESERVING_SORTS, n)


def order_preserving_sort_count() -> int:
    return _ORDER_PRESERVING_SORTS.value


def record_run_collapsed_rows(n: int) -> None:
    """Accumulate rows the run-granular aggregate path collapsed away
    (input rows minus merged runs, per collapsed update batch)."""
    _RUN_COLLAPSED_ROWS.add(n)
    _note(RUN_COLLAPSED_ROWS, n)


def run_collapsed_row_count() -> int:
    return _RUN_COLLAPSED_ROWS.value


# ---------------------------------------------------------------------------
# Adaptive-execution accounting (spark_rapids_tpu/aqe/)
# ---------------------------------------------------------------------------
_AQE_REPLANS = Metric(AQE_REPLANS)
_SKEW_SPLITS = Metric(SKEW_SPLITS)
_JOIN_DEMOTIONS = Metric(JOIN_DEMOTIONS)
_JOIN_PROMOTIONS = Metric(JOIN_PROMOTIONS)


_CANCELLED_QUERIES = Metric(CANCELLED_QUERIES)
_DEADLINE_REJECTS = Metric(DEADLINE_REJECTS)
_SHED_QUERIES = Metric(SHED_QUERIES)
_HOST_PLACED_OPS = Metric(HOST_PLACED_OPS)
_PLACEMENT_REPLACEMENTS = Metric(PLACEMENT_REPLACEMENTS)


def record_cancelled_query(n: int = 1) -> None:
    """Count one query that terminated with TpuQueryCancelled (explicit
    cancel, drain, or a mid-flight deadline expiry) — terminal by the
    engine/cancel.py contract: no retry, no fallback, no partial rows."""
    _CANCELLED_QUERIES.add(n)
    _note(CANCELLED_QUERIES, n)


def cancelled_query_count() -> int:
    return _CANCELLED_QUERIES.value


def record_deadline_reject(n: int = 1) -> None:
    """Count one query rejected BEFORE execution because its deadline was
    already spent or its predicted work could not fit the remaining
    budget (zero device dispatches)."""
    _DEADLINE_REJECTS.add(n)
    _note(DEADLINE_REJECTS, n)


def deadline_reject_count() -> int:
    return _DEADLINE_REJECTS.value


def record_shed_query(n: int = 1) -> None:
    """Count one query the overload policy shed (bounded admission queue
    depth, max queue wait, or a draining server) instead of admitting it
    to die waiting."""
    _SHED_QUERIES.add(n)
    _note(SHED_QUERIES, n)


def shed_query_count() -> int:
    return _SHED_QUERIES.value


def record_aqe_replan(n: int = 1) -> None:
    """Count one adaptive re-plan: a rule pass rewrote the not-yet-
    executed remainder and the rewrite passed static re-validation
    (verify + measured-stats resource analysis)."""
    _AQE_REPLANS.add(n)
    _note(AQE_REPLANS, n)


def aqe_replan_count() -> int:
    return _AQE_REPLANS.value


def record_host_placed_ops(n: int = 1) -> None:
    """Count operators the placement analyzer moved host-side in the
    plan this query actually executed."""
    _HOST_PLACED_OPS.add(n)
    _note(HOST_PLACED_OPS, n)


def host_placed_op_count() -> int:
    return _HOST_PLACED_OPS.value


def record_placement_replacement(n: int = 1) -> None:
    """Count one post-plan re-placement: AQE contradicting the static
    estimate with measured stats, or a device failure re-placed onto
    the host instead of degrading the whole query to CPU fallback."""
    _PLACEMENT_REPLACEMENTS.add(n)
    _note(PLACEMENT_REPLACEMENTS, n)


def placement_replacement_count() -> int:
    return _PLACEMENT_REPLACEMENTS.value


def record_skew_split(n: int = 1) -> None:
    """Count oversized reduce buckets split into piece-range
    sub-partitions by the skew-split rule."""
    _SKEW_SPLITS.add(n)
    _note(SKEW_SPLITS, n)


def skew_split_count() -> int:
    return _SKEW_SPLITS.value


def record_join_demotion(n: int = 1) -> None:
    """Count one runtime shuffled->broadcast join rewrite (measured build
    side fit under autoBroadcastJoinThreshold)."""
    _JOIN_DEMOTIONS.add(n)
    _note(JOIN_DEMOTIONS, n)


def join_demotion_count() -> int:
    return _JOIN_DEMOTIONS.value


def record_join_promotion(n: int = 1) -> None:
    """Count one runtime broadcast->shuffled join rewrite (a blown
    plan-time build-size estimate measured past the threshold)."""
    _JOIN_PROMOTIONS.add(n)
    _note(JOIN_PROMOTIONS, n)


def join_promotion_count() -> int:
    return _JOIN_PROMOTIONS.value


# ---------------------------------------------------------------------------
# Self-healing accounting (engine/scheduler.py speculation,
# engine/watchdog.py, memory/device_manager.py quarantine)
# ---------------------------------------------------------------------------
_SPECULATIVE_TASKS = Metric(SPECULATIVE_TASKS)
_SPECULATIVE_WINS = Metric(SPECULATIVE_WINS)
_WATCHDOG_KILLS = Metric(WATCHDOG_KILLS)
_DEVICE_RESETS = Metric(DEVICE_RESETS)


def record_speculative_task(n: int = 1) -> None:
    """Count one speculative duplicate launched for a straggling task
    (an idempotent re-execution from source, never shared buffers)."""
    _SPECULATIVE_TASKS.add(n)
    _note(SPECULATIVE_TASKS, n)


def speculative_task_count() -> int:
    return _SPECULATIVE_TASKS.value


def record_speculative_win(n: int = 1) -> None:
    """Count one speculative duplicate that finished before its original
    (the original was cancelled through its task-scoped token)."""
    _SPECULATIVE_WINS.add(n)
    _note(SPECULATIVE_WINS, n)


def speculative_win_count() -> int:
    return _SPECULATIVE_WINS.value


def record_watchdog_kill(n: int = 1) -> None:
    """Count one in-flight dispatch the watchdog classified wedged:
    released to raise a retryable TpuDispatchWedged, or — past the
    escalation grace — killed through the owning query's token."""
    _WATCHDOG_KILLS.add(n)
    _note(WATCHDOG_KILLS, n)


def watchdog_kill_count() -> int:
    return _WATCHDOG_KILLS.value


def record_device_reset(n: int = 1) -> None:
    """Count one device-loss event (unavailable/reset family): the
    device quarantined and the session entered recovery."""
    _DEVICE_RESETS.add(n)
    _note(DEVICE_RESETS, n)


def device_reset_count() -> int:
    return _DEVICE_RESETS.value


@contextlib.contextmanager
def trace_range(name: str, metric: Optional[Metric] = None):
    """NvtxWithMetrics analog: XProf trace annotation + elapsed-ns metric.

    THE operator-span chokepoint: every kernel/transfer site already
    wraps its device work in trace_range, so when the ambient query is
    traced (obs/trace.py) the same call opens an operator span — the
    span tree gets per-operator timing with no new instrumentation
    sites. Host clock only; no device syncs."""
    ctx = _QUERY_CTX.get()
    tr = ctx.trace if ctx is not None else None
    handle = tr.open_span(name, "op") if tr is not None else None
    start = time.perf_counter_ns()
    if _TraceAnnotation is not None:
        cm = _TraceAnnotation(name)
    else:  # pragma: no cover
        cm = contextlib.nullcontext()
    with cm:
        try:
            yield
        finally:
            if metric is not None:
                metric.add(time.perf_counter_ns() - start)
            if handle is not None:
                tr.close_span(handle)
