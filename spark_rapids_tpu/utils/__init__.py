"""Utilities: metrics/tracing, resource management, fuzz data generation."""
