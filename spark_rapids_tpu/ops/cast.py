"""Cast (reference: GpuCast.scala, 867 LoC — per-direction compat flags,
date/timestamp special cases; conf gates RapidsConf.scala:393-425).

Device-supported directions (round 1): numeric<->numeric, bool<->numeric,
date<->timestamp, timestamp<->long, int->string, date->string. String->numeric
and float->string run on the CPU path (gated by the same conf keys the
reference uses); the meta layer tags them for fallback on device.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType, DecimalType, is_decimal
from spark_rapids_tpu.ops import decimal_util as DU
from spark_rapids_tpu.ops.base import UnaryExpression
from spark_rapids_tpu.ops.values import ColV

MICROS_PER_DAY = 86_400_000_000
MICROS_PER_SEC = 1_000_000


class Cast(UnaryExpression):
    def __init__(self, child, to_type: DataType, ansi: bool = False):
        super().__init__(child)
        self.to_type = to_type
        self.ansi = ansi

    def with_children(self, new_children):
        return Cast(new_children[0], self.to_type, self.ansi)

    @property
    def data_type(self):
        return self.to_type

    def _fingerprint_extra(self):
        # ansi changes compiled behavior (deferred error flags), so it must
        # key the jit cache
        return f"->{self.to_type.name};ansi={int(self.ansi)};"

    def result_vrange(self, v):
        """Integral widening/identity casts preserve the child's value
        bounds; an int->int cast to a *narrower* type may wrap, so only
        claim a range when the child provably fits the target."""
        frm, to = self.child.data_type, self.to_type
        if not (frm.is_integral and to.is_integral):
            return None
        from spark_rapids_tpu.ops.base import val_interval

        iv = val_interval(v)
        if iv is None:
            info = np.iinfo(frm.to_np())
            iv = (int(info.min), int(info.max))
        tinfo = np.iinfo(to.to_np())
        if iv[0] >= int(tinfo.min) and iv[1] <= int(tinfo.max):
            return iv
        return None

    # which (from, to) directions the device kernel handles
    @staticmethod
    def device_supported(frm, to) -> bool:
        if frm == to:
            return True
        numeric_ish = {DataType.BOOL, DataType.INT8, DataType.INT16,
                       DataType.INT32, DataType.INT64, DataType.FLOAT32,
                       DataType.FLOAT64}
        if is_decimal(frm):
            # decimal -> numeric/decimal is pure int64 math on device
            return is_decimal(to) or to in numeric_ish
        if is_decimal(to):
            # float -> decimal stays on the host oracle: Spark rounds via the
            # double's shortest decimal repr (BigDecimal.valueOf), which has
            # no jittable equivalent (cf. the reference gating float casts,
            # RapidsConf.scala:393-425)
            return frm in numeric_ish and not frm.is_floating
        if frm in numeric_ish and to in numeric_ish:
            return True
        if frm is DataType.DATE and to in (DataType.TIMESTAMP, DataType.STRING,
                                           DataType.INT32):
            return True
        if frm is DataType.TIMESTAMP and to in (DataType.DATE, DataType.INT64,
                                                DataType.STRING):
            return True
        if frm in (DataType.BOOL, DataType.INT8, DataType.INT16,
                   DataType.INT32, DataType.INT64) and to is DataType.STRING:
            return True
        if frm is DataType.INT64 and to is DataType.TIMESTAMP:
            return True
        return False

    def do_columnar(self, ctx, v):
        frm, to = self.child.data_type, self.to_type
        if frm == to:
            return v.data if to is not DataType.STRING else v
        if to is DataType.STRING:
            return self._to_string(ctx, v, frm)
        if frm is DataType.STRING:
            return self._from_string(ctx, v, to)
        return self._numeric_datetime(ctx, v, frm, to)

    # -- decimal --------------------------------------------------------------
    def _decimal(self, ctx, v, frm, to):
        """Casts with a decimal endpoint; overflow -> SQL NULL (non-ANSI) or
        raises (ANSI), matching Spark's Decimal.changePrecision."""
        xp = ctx.xp
        data = v.data
        if is_decimal(frm) and is_decimal(to):
            out, ok1 = DU.rescale(xp, data, frm.scale, to.scale)
            out, ok2 = DU.fit_precision(xp, out, to.precision)
            return self._dec_result(ctx, v, to, out, ok1 & ok2)
        if is_decimal(frm):
            if to is DataType.BOOL:
                return data != 0
            if to.is_floating:
                npdt = self._phys(ctx, to)
                return data.astype(npdt) / npdt.type(float(DU.POW10[frm.scale]))
            if to.is_integral:
                # truncate toward zero, overflow -> null
                q = xp.abs(data) // DU.POW10[frm.scale]
                q = xp.where(data < 0, -q, q)
                info = np.iinfo(to.to_np())
                ok = (q >= info.min) & (q <= info.max)
                out = xp.where(ok, q, 0).astype(self._phys(ctx, to))
                return self._dec_result(ctx, v, to, out, ok)
            raise NotImplementedError(f"cast {frm} -> {to}")
        # numeric -> decimal
        if frm is DataType.BOOL:
            out = data.astype(np.int64) * DU.POW10[to.scale]
            return self._dec_result(ctx, v, to, out,
                                    xp.ones_like(out, dtype=bool))
        if frm.is_integral:
            out, ok1 = DU.checked_mul_pow10(xp, data.astype(np.int64),
                                            to.scale)
            out, ok2 = DU.fit_precision(xp, out, to.precision)
            return self._dec_result(ctx, v, to, out, ok1 & ok2)
        if frm.is_floating:
            if ctx.is_device:
                # approximate path (direct kernel use only; the plan layer
                # keeps this direction on the host oracle): binary-float
                # HALF_UP at target scale; NaN/Inf/overflow -> null
                scaled = data * float(DU.POW10[to.scale])
                finite = xp.isfinite(scaled)
                limit = float(DU.bound(to.precision))
                ok = finite & (xp.abs(scaled) <= limit)
                half = xp.where(scaled >= 0, 0.5, -0.5)
                out = xp.where(ok, scaled + half, 0.0).astype(np.int64)
                out, ok2 = DU.fit_precision(xp, out, to.precision)
                return self._dec_result(ctx, v, to, out, ok & ok2)
            # host: Spark-exact — round the double's shortest decimal repr
            # (BigDecimal.valueOf semantics), HALF_UP at target scale
            out = np.zeros(len(data), dtype=np.int64)
            ok = np.zeros(len(data), dtype=bool)
            limit = int(DU.bound(to.precision))
            for i, x in enumerate(data):
                x = float(x)
                if not np.isfinite(x):
                    continue
                try:
                    u = DU.to_unscaled(x, to.scale)
                except OverflowError:
                    continue
                if abs(u) <= limit:
                    out[i] = u
                    ok[i] = True
            return self._dec_result(ctx, v, to, out, ok)
        raise NotImplementedError(f"cast {frm} -> {to}")

    def _dec_result(self, ctx, v, to, out, ok):
        if self.ansi:
            overflow = v.validity & ~ok
            if not ctx.is_device and bool(np.asarray(overflow).any()):
                raise ArithmeticError(
                    f"cast to {getattr(to, 'value', to)} overflowed (ANSI)")
        return ColV(to, out, ok)

    def _phys(self, ctx, dt):
        if ctx.is_device:
            from spark_rapids_tpu.columnar.batch import physical_np_dtype

            return physical_np_dtype(dt)
        return dt.to_np()

    # -- numeric / datetime --------------------------------------------------
    def _numeric_datetime(self, ctx, v, frm, to):
        xp = ctx.xp
        if is_decimal(frm) or is_decimal(to):
            return self._decimal(ctx, v, frm, to)
        data = v.data
        if ctx.is_device:
            from spark_rapids_tpu.columnar.batch import physical_np_dtype

            npdt = physical_np_dtype(to)
        else:
            npdt = to.to_np()
        if frm is DataType.DATE and to is DataType.TIMESTAMP:
            return data.astype(np.int64) * MICROS_PER_DAY
        if frm is DataType.TIMESTAMP and to is DataType.DATE:
            return (data // MICROS_PER_DAY).astype(np.int32)
        if frm is DataType.TIMESTAMP and to is DataType.INT64:
            # spark: epoch seconds, floored
            return data // MICROS_PER_SEC
        if frm is DataType.INT64 and to is DataType.TIMESTAMP:
            # explicit widen: an int32-narrowed LONG would wrap at *1e6
            return data.astype(np.int64) * MICROS_PER_SEC
        if to is DataType.BOOL:
            return data != 0
        if frm.is_floating and to.is_integral:
            # spark truncates toward zero; NaN -> 0, out-of-range saturates
            # (non-ansi). float(int64.max) rounds up to 2^63, so saturate via
            # comparisons instead of clip-then-astype (which would wrap).
            clean = xp.where(xp.isnan(data), 0.0, data)
            t = xp.trunc(clean)
            info = np.iinfo(npdt)
            res = t.astype(npdt)
            res = xp.where(t >= float(info.max), info.max, res)
            res = xp.where(t <= float(info.min), info.min, res)
            return res
        return data.astype(npdt)

    # -- to string -----------------------------------------------------------
    def _to_string(self, ctx, v, frm):
        if not ctx.is_device:
            return self._to_string_host(ctx, v, frm)
        from spark_rapids_tpu.columnar import format as F

        if frm.is_integral or frm is DataType.BOOL:
            return F.int_to_string(ctx, v)
        if frm is DataType.DATE:
            return F.date_to_string(ctx, v)
        if frm is DataType.TIMESTAMP:
            return F.timestamp_to_string(ctx, v)
        if frm.is_floating:
            # planner admits this direction only when
            # rapids.tpu.sql.castFloatToString.enabled is set AND the
            # backend carries real f64 lanes (the shared shortest-decimal
            # search runs in f64; overrides.py:_tag_cast)
            return F.float_to_string(ctx, v)
        raise NotImplementedError(f"device cast {frm} -> STRING")

    def _to_string_host(self, ctx, v, frm):
        if frm.is_floating:
            return format_float_array(np.asarray(v.data),
                                      frm is DataType.FLOAT32)

        def fmt(x):
            if is_decimal(frm):
                return str(DU.from_unscaled(int(x), frm.scale))
            if frm is DataType.BOOL:
                return "true" if x else "false"
            if frm.is_integral:
                return str(int(x))
            if frm is DataType.DATE:
                return _date_str(int(x))
            if frm is DataType.TIMESTAMP:
                return _ts_str(int(x))
            raise NotImplementedError(f"cast {frm} -> STRING")

        return np.array([fmt(x) for x in v.data], dtype=object)

    # -- from string ---------------------------------------------------------
    def _from_string(self, ctx, v, to):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import parse as PRS

            if to.is_floating:
                out, malformed = PRS.parse_float_col(ctx, v, to)
            elif to is DataType.TIMESTAMP:
                out, malformed = PRS.parse_timestamp_col(ctx, v)
            else:
                raise NotImplementedError(f"device cast STRING -> {to}")
            if self.ansi:
                import jax.numpy as jnp

                # deferred ANSI error: can't raise mid-trace; the evaluator
                # entry point checks the flag after the jitted call
                ctx.ansi_errors.append((
                    jnp.any(malformed),
                    f"ANSI cast STRING -> {to.name}: malformed input"))
            return out
        out = np.zeros(len(v.data), dtype=to.to_np())
        validity = v.validity.copy()
        for i, s in enumerate(v.data):
            if not validity[i]:
                continue
            # ASCII whitespace only: the device trim (columnar/parse.py)
            # cannot see Unicode spaces, and host/device must agree on
            # exactly which inputs parse (advisor round 4)
            s = s.strip(" \t\n\r\f\x0b")
            try:
                if is_decimal(to):
                    u = DU.to_unscaled(s, to.scale)
                    if abs(u) > int(DU.bound(to.precision)):
                        raise OverflowError(s)
                    out[i] = u
                elif to.is_integral:
                    out[i] = int(float(s)) if "." in s or "e" in s.lower() else int(s)
                elif to.is_floating:
                    out[i] = _parse_float_text(s)
                elif to is DataType.BOOL:
                    low = s.lower()
                    if low in ("t", "true", "y", "yes", "1"):
                        out[i] = True
                    elif low in ("f", "false", "n", "no", "0"):
                        out[i] = False
                    else:
                        raise ValueError(s)
                elif to is DataType.DATE:
                    out[i] = _parse_date(s)
                elif to is DataType.TIMESTAMP:
                    out[i] = _parse_ts_strict(s)
                else:
                    raise NotImplementedError(f"cast STRING -> {to}")
            except (ValueError, OverflowError, ArithmeticError):
                if self.ansi:
                    raise
                validity[i] = False
                out[i] = 0
        if to is DataType.FLOAT32:
            # shared convention with the device parse kernel: sub-normal
            # f32 results flush to signed zero (columnar/parse.py)
            tiny = np.isfinite(out) & (np.abs(out) < 2.0 ** -126)
            out[tiny] = np.copysign(np.float32(0.0), out[tiny])
        return ColV(to, out, validity & v.validity)

def _date_str(days: int) -> str:
    # integer civil math, not datetime.date (which caps years at 9999 and
    # raises beyond; DATE is the full int32 days domain). Byte-identical
    # to the device kernel (columnar/format.py:date_to_string).
    from spark_rapids_tpu.ops import datetimeops as DT

    y, m, d = DT.civil_from_days(np, np.asarray([days], dtype=np.int64))
    return f"{_year_str(int(y[0]))}-{int(m[0]):02d}-{int(d[0]):02d}"


def _year_str(y: int) -> str:
    """Year formatting shared by date/timestamp casts: 4-digit zero-padded
    inside [0, 9999], explicit sign + >= 4 digits outside (Java
    DateTimeFormatter SignStyle.EXCEEDS_PAD, which Spark's uuuu pattern
    uses: 10000 -> '+10000', -5 -> '-0005')."""
    if 0 <= y <= 9999:
        return f"{y:04d}"
    sign = "-" if y < 0 else "+"
    return f"{sign}{abs(y):04d}"


def _ts_str(micros: int) -> str:
    # pure integer civil-calendar math, NOT datetime/strftime: datetime
    # caps years at [1, 9999] (raising beyond) and glibc's %Y does not
    # zero-pad — while SQL timestamps span the full int64 micros domain
    # (years +-294k). Must stay byte-identical to the device kernel
    # (columnar/format.py:timestamp_to_string).
    from spark_rapids_tpu.ops import datetimeops as DT

    days, rem = divmod(micros, MICROS_PER_DAY)
    y, m, d = DT.civil_from_days(np, np.asarray([days], dtype=np.int64))
    secs, frac = divmod(rem, MICROS_PER_SEC)
    base = (f"{_year_str(int(y[0]))}-{int(m[0]):02d}-{int(d[0]):02d} "
            f"{secs // 3600:02d}:{secs % 3600 // 60:02d}:{secs % 60:02d}")
    if frac:
        return f"{base}.{frac:06d}".rstrip("0")
    return base


def _parse_date(s: str) -> int:
    import datetime

    return (datetime.date.fromisoformat(s) - datetime.date(1970, 1, 1)).days


import re as _re

_FLOAT_RE = _re.compile(
    r"^[+-]?(?:(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][+-]?\d{1,3})?|"
    r"(?i:inf|infinity|nan))$")
_TS_RE = _re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})"
    r"(?:[ T](\d{2}):(\d{2}):(\d{2})(?:\.(\d{1,6}))?"
    r"(Z|[+-]\d{2}:\d{2})?)?$")


def _parse_float_text(s: str) -> float:
    """Host mirror of the device STRING->float kernel
    (columnar/parse.py:_parse_float_kernel): same grammar, same 17-digit
    mantissa fold, same shared-table scaling — values agree bitwise with
    the device (raises ValueError on grammar violations)."""
    from spark_rapids_tpu.columnar import format as F

    if len(s) > 48 or not _FLOAT_RE.match(s):
        raise ValueError(s)
    low = s.lstrip("+-").lower()
    negv = s.startswith("-")
    if low in ("inf", "infinity"):
        return -np.inf if negv else np.inf
    if low == "nan":
        return np.nan
    mant, _, ex = low.partition("e")
    ipart, _, fpart = mant.partition(".")
    m = 0
    nsig = 0
    dropped_int = 0
    scale = 0
    for d in ipart:
        if nsig < 17:
            m = m * 10 + int(d)
            if m > 0:
                nsig += 1
        else:
            dropped_int += 1
    for d in fpart:
        if nsig < 17:
            m = m * 10 + int(d)
            scale += 1
            if m > 0:
                nsig += 1
    q = (int(ex) if ex else 0) - scale + dropped_int
    val = float(F.f64_scale_int(np, np.int64(m),
                                np.int64(max(-400, min(400, q)))))
    return -val if negv else val


def _parse_ts_strict(s: str) -> int:
    """Host mirror of the device STRING->TIMESTAMP kernel
    (columnar/parse.py:_parse_timestamp_kernel): strict 'YYYY-MM-DD' /
    'YYYY-MM-DD[ T]HH:MM:SS[.f{1,6}][Z|+-HH:MM]' grammar, naive = UTC,
    integer epoch math (raises ValueError on violations)."""
    mt = _TS_RE.match(s)
    if not mt:
        raise ValueError(s)
    from spark_rapids_tpu.ops import datetimeops as DT

    y, mo, d = int(mt.group(1)), int(mt.group(2)), int(mt.group(3))
    days = int(DT.days_from_civil(np, np.int64(y), np.int64(mo),
                                  np.int64(d)))
    ry, rm, rd = DT.civil_from_days(np, np.int64(days))
    if (int(ry), int(rm), int(rd)) != (y, mo, d):
        raise ValueError(s)
    micros = days * 86_400_000_000
    if mt.group(4) is not None:
        hh, mi, ss = int(mt.group(4)), int(mt.group(5)), int(mt.group(6))
        if hh >= 24 or mi >= 60 or ss >= 60:
            raise ValueError(s)
        frac = (mt.group(7) or "").ljust(6, "0")
        micros += (hh * 3600 + mi * 60 + ss) * MICROS_PER_SEC + int(frac)
        z = mt.group(8)
        if z and z != "Z":
            zh, zm = int(z[1:3]), int(z[4:6])
            if zh >= 24 or zm >= 60:
                raise ValueError(s)
            off = zh * 60 + zm
            if z[0] == "-":
                off = -off
            micros -= off * 60_000_000
    return micros


def _parse_ts(s: str) -> int:
    import datetime

    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    delta = dt - datetime.datetime(1970, 1, 1)
    return (delta.days * 86_400 + delta.seconds) * MICROS_PER_SEC + delta.microseconds


def _emit_float_digits(m: int, p: int, e10: int, neg: bool) -> str:
    """Render a (mantissa, precision, exponent) decomposition Java-style:
    plain decimal for -3 <= e10 < 7, else 'd.dddE[-]ee'. Pure integer
    logic — the device emitter (columnar/format.py float_to_string)
    implements the identical placement rules, so given identical
    decompositions the bytes are identical."""
    digs = str(m).rjust(p, "0")
    sign = "-" if neg else ""
    if -3 <= e10 < 7:
        if e10 >= p - 1:
            body = digs + "0" * (e10 - p + 1) + ".0"
        elif e10 >= 0:
            body = digs[:e10 + 1] + "." + digs[e10 + 1:]
        else:
            body = "0." + "0" * (-e10 - 1) + digs
        return sign + body
    frac = digs[1:] if p > 1 else "0"
    return f"{sign}{digs[0]}.{frac}E{e10}"


def format_float_array(vals: np.ndarray, is32: bool) -> np.ndarray:
    """Host float->string with the SAME shortest-round-trip algorithm as
    the device kernel (shared core shortest_float_decomposition run with
    xp=numpy): the framework's float formatting convention. Replaces the
    earlier repr()-based formatter so host and device agree bytewise."""
    from spark_rapids_tpu.columnar import format as F

    x = np.ascontiguousarray(vals,
                             dtype=np.float32 if is32 else np.float64)
    f64 = x.astype(np.float64)
    a = np.abs(f64)
    nan = np.isnan(f64)
    inf = np.isinf(f64)
    zero = a == 0.0
    neg = np.signbit(f64)
    finite = ~(nan | inf | zero)
    with np.errstate(over="ignore", invalid="ignore"):
        m, p, e10 = F.shortest_float_decomposition(
            np, np.where(finite, a, 1.0), 9 if is32 else 17, is32=is32)
    out = np.empty(len(x), dtype=object)
    for i in range(len(x)):
        if nan[i]:
            out[i] = "NaN"
        elif inf[i]:
            out[i] = "-Infinity" if neg[i] else "Infinity"
        elif zero[i]:
            out[i] = "-0.0" if neg[i] else "0.0"
        else:
            out[i] = _emit_float_digits(int(m[i]), int(p[i]), int(e10[i]),
                                        bool(neg[i]))
    return out


