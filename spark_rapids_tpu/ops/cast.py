"""Cast (reference: GpuCast.scala, 867 LoC — per-direction compat flags,
date/timestamp special cases; conf gates RapidsConf.scala:393-425).

Device-supported directions (round 1): numeric<->numeric, bool<->numeric,
date<->timestamp, timestamp<->long, int->string, date->string. String->numeric
and float->string run on the CPU path (gated by the same conf keys the
reference uses); the meta layer tags them for fallback on device.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType, DecimalType, is_decimal
from spark_rapids_tpu.ops import decimal_util as DU
from spark_rapids_tpu.ops.base import UnaryExpression
from spark_rapids_tpu.ops.values import ColV

MICROS_PER_DAY = 86_400_000_000
MICROS_PER_SEC = 1_000_000


class Cast(UnaryExpression):
    def __init__(self, child, to_type: DataType, ansi: bool = False):
        super().__init__(child)
        self.to_type = to_type
        self.ansi = ansi

    def with_children(self, new_children):
        return Cast(new_children[0], self.to_type, self.ansi)

    @property
    def data_type(self):
        return self.to_type

    def _fingerprint_extra(self):
        return f"->{self.to_type.name};"

    def result_vrange(self, v):
        """Integral widening/identity casts preserve the child's value
        bounds; an int->int cast to a *narrower* type may wrap, so only
        claim a range when the child provably fits the target."""
        frm, to = self.child.data_type, self.to_type
        if not (frm.is_integral and to.is_integral):
            return None
        from spark_rapids_tpu.ops.base import val_interval

        iv = val_interval(v)
        if iv is None:
            info = np.iinfo(frm.to_np())
            iv = (int(info.min), int(info.max))
        tinfo = np.iinfo(to.to_np())
        if iv[0] >= int(tinfo.min) and iv[1] <= int(tinfo.max):
            return iv
        return None

    # which (from, to) directions the device kernel handles
    @staticmethod
    def device_supported(frm, to) -> bool:
        if frm == to:
            return True
        numeric_ish = {DataType.BOOL, DataType.INT8, DataType.INT16,
                       DataType.INT32, DataType.INT64, DataType.FLOAT32,
                       DataType.FLOAT64}
        if is_decimal(frm):
            # decimal -> numeric/decimal is pure int64 math on device
            return is_decimal(to) or to in numeric_ish
        if is_decimal(to):
            # float -> decimal stays on the host oracle: Spark rounds via the
            # double's shortest decimal repr (BigDecimal.valueOf), which has
            # no jittable equivalent (cf. the reference gating float casts,
            # RapidsConf.scala:393-425)
            return frm in numeric_ish and not frm.is_floating
        if frm in numeric_ish and to in numeric_ish:
            return True
        if frm is DataType.DATE and to in (DataType.TIMESTAMP, DataType.STRING,
                                           DataType.INT32):
            return True
        if frm is DataType.TIMESTAMP and to in (DataType.DATE, DataType.INT64,
                                                DataType.STRING):
            return True
        if frm in (DataType.BOOL, DataType.INT8, DataType.INT16,
                   DataType.INT32, DataType.INT64) and to is DataType.STRING:
            return True
        if frm is DataType.INT64 and to is DataType.TIMESTAMP:
            return True
        return False

    def do_columnar(self, ctx, v):
        frm, to = self.child.data_type, self.to_type
        if frm == to:
            return v.data if to is not DataType.STRING else v
        if to is DataType.STRING:
            return self._to_string(ctx, v, frm)
        if frm is DataType.STRING:
            return self._from_string(ctx, v, to)
        return self._numeric_datetime(ctx, v, frm, to)

    # -- decimal --------------------------------------------------------------
    def _decimal(self, ctx, v, frm, to):
        """Casts with a decimal endpoint; overflow -> SQL NULL (non-ANSI) or
        raises (ANSI), matching Spark's Decimal.changePrecision."""
        xp = ctx.xp
        data = v.data
        if is_decimal(frm) and is_decimal(to):
            out, ok1 = DU.rescale(xp, data, frm.scale, to.scale)
            out, ok2 = DU.fit_precision(xp, out, to.precision)
            return self._dec_result(ctx, v, to, out, ok1 & ok2)
        if is_decimal(frm):
            if to is DataType.BOOL:
                return data != 0
            if to.is_floating:
                npdt = self._phys(ctx, to)
                return data.astype(npdt) / npdt.type(float(DU.POW10[frm.scale]))
            if to.is_integral:
                # truncate toward zero, overflow -> null
                q = xp.abs(data) // DU.POW10[frm.scale]
                q = xp.where(data < 0, -q, q)
                info = np.iinfo(to.to_np())
                ok = (q >= info.min) & (q <= info.max)
                out = xp.where(ok, q, 0).astype(self._phys(ctx, to))
                return self._dec_result(ctx, v, to, out, ok)
            raise NotImplementedError(f"cast {frm} -> {to}")
        # numeric -> decimal
        if frm is DataType.BOOL:
            out = data.astype(np.int64) * DU.POW10[to.scale]
            return self._dec_result(ctx, v, to, out,
                                    xp.ones_like(out, dtype=bool))
        if frm.is_integral:
            out, ok1 = DU.checked_mul_pow10(xp, data.astype(np.int64),
                                            to.scale)
            out, ok2 = DU.fit_precision(xp, out, to.precision)
            return self._dec_result(ctx, v, to, out, ok1 & ok2)
        if frm.is_floating:
            if ctx.is_device:
                # approximate path (direct kernel use only; the plan layer
                # keeps this direction on the host oracle): binary-float
                # HALF_UP at target scale; NaN/Inf/overflow -> null
                scaled = data * float(DU.POW10[to.scale])
                finite = xp.isfinite(scaled)
                limit = float(DU.bound(to.precision))
                ok = finite & (xp.abs(scaled) <= limit)
                half = xp.where(scaled >= 0, 0.5, -0.5)
                out = xp.where(ok, scaled + half, 0.0).astype(np.int64)
                out, ok2 = DU.fit_precision(xp, out, to.precision)
                return self._dec_result(ctx, v, to, out, ok & ok2)
            # host: Spark-exact — round the double's shortest decimal repr
            # (BigDecimal.valueOf semantics), HALF_UP at target scale
            out = np.zeros(len(data), dtype=np.int64)
            ok = np.zeros(len(data), dtype=bool)
            limit = int(DU.bound(to.precision))
            for i, x in enumerate(data):
                x = float(x)
                if not np.isfinite(x):
                    continue
                try:
                    u = DU.to_unscaled(x, to.scale)
                except OverflowError:
                    continue
                if abs(u) <= limit:
                    out[i] = u
                    ok[i] = True
            return self._dec_result(ctx, v, to, out, ok)
        raise NotImplementedError(f"cast {frm} -> {to}")

    def _dec_result(self, ctx, v, to, out, ok):
        if self.ansi:
            overflow = v.validity & ~ok
            if not ctx.is_device and bool(np.asarray(overflow).any()):
                raise ArithmeticError(
                    f"cast to {getattr(to, 'value', to)} overflowed (ANSI)")
        return ColV(to, out, ok)

    def _phys(self, ctx, dt):
        if ctx.is_device:
            from spark_rapids_tpu.columnar.batch import physical_np_dtype

            return physical_np_dtype(dt)
        return dt.to_np()

    # -- numeric / datetime --------------------------------------------------
    def _numeric_datetime(self, ctx, v, frm, to):
        xp = ctx.xp
        if is_decimal(frm) or is_decimal(to):
            return self._decimal(ctx, v, frm, to)
        data = v.data
        if ctx.is_device:
            from spark_rapids_tpu.columnar.batch import physical_np_dtype

            npdt = physical_np_dtype(to)
        else:
            npdt = to.to_np()
        if frm is DataType.DATE and to is DataType.TIMESTAMP:
            return data.astype(np.int64) * MICROS_PER_DAY
        if frm is DataType.TIMESTAMP and to is DataType.DATE:
            return (data // MICROS_PER_DAY).astype(np.int32)
        if frm is DataType.TIMESTAMP and to is DataType.INT64:
            # spark: epoch seconds, floored
            return data // MICROS_PER_SEC
        if frm is DataType.INT64 and to is DataType.TIMESTAMP:
            # explicit widen: an int32-narrowed LONG would wrap at *1e6
            return data.astype(np.int64) * MICROS_PER_SEC
        if to is DataType.BOOL:
            return data != 0
        if frm.is_floating and to.is_integral:
            # spark truncates toward zero; NaN -> 0, out-of-range saturates
            # (non-ansi). float(int64.max) rounds up to 2^63, so saturate via
            # comparisons instead of clip-then-astype (which would wrap).
            clean = xp.where(xp.isnan(data), 0.0, data)
            t = xp.trunc(clean)
            info = np.iinfo(npdt)
            res = t.astype(npdt)
            res = xp.where(t >= float(info.max), info.max, res)
            res = xp.where(t <= float(info.min), info.min, res)
            return res
        return data.astype(npdt)

    # -- to string -----------------------------------------------------------
    def _to_string(self, ctx, v, frm):
        if not ctx.is_device:
            return self._to_string_host(ctx, v, frm)
        from spark_rapids_tpu.columnar import format as F

        if frm.is_integral or frm is DataType.BOOL:
            return F.int_to_string(ctx, v)
        if frm is DataType.DATE:
            return F.date_to_string(ctx, v)
        if frm is DataType.TIMESTAMP:
            return F.timestamp_to_string(ctx, v)
        raise NotImplementedError(f"device cast {frm} -> STRING")

    def _to_string_host(self, ctx, v, frm):
        def fmt(x):
            if is_decimal(frm):
                return str(DU.from_unscaled(int(x), frm.scale))
            if frm is DataType.BOOL:
                return "true" if x else "false"
            if frm.is_integral:
                return str(int(x))
            if frm is DataType.DATE:
                return _date_str(int(x))
            if frm is DataType.TIMESTAMP:
                return _ts_str(int(x))
            if frm.is_floating:
                return _spark_float_str(float(x))
            raise NotImplementedError(f"cast {frm} -> STRING")

        return np.array([fmt(x) for x in v.data], dtype=object)

    # -- from string (CPU only in round 1) -----------------------------------
    def _from_string(self, ctx, v, to):
        if ctx.is_device:
            raise NotImplementedError("device cast STRING -> x (round 2)")
        out = np.zeros(len(v.data), dtype=to.to_np())
        validity = v.validity.copy()
        for i, s in enumerate(v.data):
            if not validity[i]:
                continue
            s = s.strip()
            try:
                if is_decimal(to):
                    u = DU.to_unscaled(s, to.scale)
                    if abs(u) > int(DU.bound(to.precision)):
                        raise OverflowError(s)
                    out[i] = u
                elif to.is_integral:
                    out[i] = int(float(s)) if "." in s or "e" in s.lower() else int(s)
                elif to.is_floating:
                    out[i] = float(s)
                elif to is DataType.BOOL:
                    low = s.lower()
                    if low in ("t", "true", "y", "yes", "1"):
                        out[i] = True
                    elif low in ("f", "false", "n", "no", "0"):
                        out[i] = False
                    else:
                        raise ValueError(s)
                elif to is DataType.DATE:
                    out[i] = _parse_date(s)
                elif to is DataType.TIMESTAMP:
                    out[i] = _parse_ts(s)
                else:
                    raise NotImplementedError(f"cast STRING -> {to}")
            except (ValueError, OverflowError, ArithmeticError):
                if self.ansi:
                    raise
                validity[i] = False
                out[i] = 0
        return ColV(to, out, validity & v.validity)

def _date_str(days: int) -> str:
    # integer civil math, not datetime.date (which caps years at 9999 and
    # raises beyond; DATE is the full int32 days domain). Byte-identical
    # to the device kernel (columnar/format.py:date_to_string).
    from spark_rapids_tpu.ops import datetimeops as DT

    y, m, d = DT.civil_from_days(np, np.asarray([days], dtype=np.int64))
    return f"{_year_str(int(y[0]))}-{int(m[0]):02d}-{int(d[0]):02d}"


def _year_str(y: int) -> str:
    """Year formatting shared by date/timestamp casts: 4-digit zero-padded
    inside [0, 9999], explicit sign + >= 4 digits outside (Java
    DateTimeFormatter SignStyle.EXCEEDS_PAD, which Spark's uuuu pattern
    uses: 10000 -> '+10000', -5 -> '-0005')."""
    if 0 <= y <= 9999:
        return f"{y:04d}"
    sign = "-" if y < 0 else "+"
    return f"{sign}{abs(y):04d}"


def _ts_str(micros: int) -> str:
    # pure integer civil-calendar math, NOT datetime/strftime: datetime
    # caps years at [1, 9999] (raising beyond) and glibc's %Y does not
    # zero-pad — while SQL timestamps span the full int64 micros domain
    # (years +-294k). Must stay byte-identical to the device kernel
    # (columnar/format.py:timestamp_to_string).
    from spark_rapids_tpu.ops import datetimeops as DT

    days, rem = divmod(micros, MICROS_PER_DAY)
    y, m, d = DT.civil_from_days(np, np.asarray([days], dtype=np.int64))
    secs, frac = divmod(rem, MICROS_PER_SEC)
    base = (f"{_year_str(int(y[0]))}-{int(m[0]):02d}-{int(d[0]):02d} "
            f"{secs // 3600:02d}:{secs % 3600 // 60:02d}:{secs % 60:02d}")
    if frac:
        return f"{base}.{frac:06d}".rstrip("0")
    return base


def _parse_date(s: str) -> int:
    import datetime

    return (datetime.date.fromisoformat(s) - datetime.date(1970, 1, 1)).days


def _parse_ts(s: str) -> int:
    import datetime

    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    delta = dt - datetime.datetime(1970, 1, 1)
    return (delta.days * 86_400 + delta.seconds) * MICROS_PER_SEC + delta.microseconds


def _spark_float_str(x: float) -> str:
    """Java Double.toString-ish (Spark formatting): 1.0 not 1, NaN, Infinity."""
    if np.isnan(x):
        return "NaN"
    if np.isinf(x):
        return "Infinity" if x > 0 else "-Infinity"
    if x == int(x) and abs(x) < 1e16:
        return f"{x:.1f}"
    return repr(x)
