"""Bitwise and shift expressions (reference: bitwise.scala, ~150 LoC)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType, common_type
from spark_rapids_tpu.ops.base import BinaryExpression, UnaryExpression, _d


def _at_logical_width(dt, x):
    """Shift semantics depend on the operand WIDTH, not just its value:
    an int32-narrowed LONG must shift as a 64-bit lane (shift amounts up
    to 63, wrap at bit 64). And/or/xor/not stay narrow — sign extension
    commutes with bitwise-parallel ops."""
    npdt = dt.to_np()
    if hasattr(x, "astype") and x.dtype != npdt and npdt.kind in "iu":
        return x.astype(npdt)
    return x


class BitwiseBinary(BinaryExpression):
    @property
    def data_type(self):
        return common_type(self.left.data_type, self.right.data_type)


class BitwiseAnd(BitwiseBinary):
    def do_columnar(self, ctx, lv, rv):
        return _d(lv) & _d(rv)


class BitwiseOr(BitwiseBinary):
    def do_columnar(self, ctx, lv, rv):
        return _d(lv) | _d(rv)


class BitwiseXor(BitwiseBinary):
    def do_columnar(self, ctx, lv, rv):
        return _d(lv) ^ _d(rv)


class BitwiseNot(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def do_columnar(self, ctx, v):
        return ~v.data


class ShiftLeft(BinaryExpression):
    @property
    def data_type(self):
        return self.left.data_type

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        bits = 64 if self.data_type is DataType.INT64 else 32
        shift = _d(rv) % bits  # java semantics: shift amount masked
        return xp.left_shift(_at_logical_width(self.data_type, _d(lv)), shift)


class ShiftRight(BinaryExpression):
    """Arithmetic (sign-extending) right shift."""

    @property
    def data_type(self):
        return self.left.data_type

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        bits = 64 if self.data_type is DataType.INT64 else 32
        shift = _d(rv) % bits
        return xp.right_shift(_at_logical_width(self.data_type, _d(lv)), shift)


class ShiftRightUnsigned(BinaryExpression):
    """Logical (zero-filling) right shift (java >>>)."""

    @property
    def data_type(self):
        return self.left.data_type

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        npdt = self.data_type.to_np()
        udt = np.dtype(np.uint64) if npdt == np.int64 else np.dtype(np.uint32)
        bits = 64 if npdt == np.int64 else 32
        shift = _d(rv) % bits
        shift = shift.astype(udt) if hasattr(shift, "astype") else udt.type(shift)
        return xp.right_shift(_d(lv).astype(udt), shift).astype(npdt)
