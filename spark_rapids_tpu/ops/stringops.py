"""String expressions (reference: stringFunctions.scala, 698 LoC — substring,
replace, trim family, starts/ends/contains, concat, like, upper/lower, length).

Device kernels live in columnar/strings.py; the CPU-oracle path here is
plain python string ops over object arrays.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import (
    BinaryExpression,
    Expression,
    TernaryExpression,
    UnaryExpression,
)
from spark_rapids_tpu.ops.values import ColV, ScalarV


def _obj(fn, *arrs):
    """Apply a python fn element-wise over object arrays."""
    return np.array([fn(*vals) for vals in zip(*arrs)], dtype=object)


def _like_regex(pattern: str):
    """Translate SQL LIKE to an anchored regex ( % -> .*, _ -> . )."""
    import re

    return re.compile(
        "^" + "".join(
            ".*" if c == "%" else "." if c == "_" else re.escape(c)
            for c in pattern
        ) + "$",
        re.DOTALL,
    )


class Length(UnaryExpression):
    """Character length (reference: GpuLength)."""

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.utf8_char_lengths(v).astype(np.int32)
        return np.array([len(s) for s in v.data], dtype=np.int32)


class Upper(UnaryExpression):
    """Uppercase; device kernel is ASCII-only (non-ASCII bytes pass through),
    flagged incompat like the reference's locale-sensitive ops."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.upper_ascii(v)
        return _obj(lambda s: s.upper(), v.data)


class Lower(UnaryExpression):
    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.lower_ascii(v)
        return _obj(lambda s: s.lower(), v.data)


class Substring(TernaryExpression):
    """substring(str, pos, len) — 1-based, negative pos from end."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, sv, pv, lv):
        from spark_rapids_tpu.ops.base import _d

        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.substring_utf8(ctx, sv, _d(pv), _d(lv))

        def sub(s, p, ln):
            p, ln = int(p), int(ln)
            if ln < 0:
                ln = 0
            if p > 0:
                start = p - 1
            elif p < 0:
                start = max(len(s) + p, 0)
            else:
                start = 0
            return s[start:start + ln]

        pos = pv.data if isinstance(pv, ColV) else np.full(ctx.capacity, pv.value)
        ln = lv.data if isinstance(lv, ColV) else np.full(ctx.capacity, lv.value)
        return _obj(sub, sv.data, pos, ln)


class Concat(BinaryExpression):
    """concat(a, b); Spark concat is variadic — the planner folds n-ary concat
    into a left-deep chain of these."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, lv, rv):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.concat2(ctx, lv, rv)

        def side(v):
            if isinstance(v, ScalarV):
                return [v.value] * ctx.capacity
            return v.data

        return _obj(lambda a, b: a + b, side(lv), side(rv))


class _NeedleOp(BinaryExpression):
    """Base for StartsWith/EndsWith/Contains: right side must be a foldable
    string literal (same restriction as the reference, which requires scalar
    needles for cudf ops)."""

    _host_fn = None
    _device_fn = None

    @property
    def data_type(self):
        return DataType.BOOL

    def eval_scalars(self, lv, rv):
        from spark_rapids_tpu.ops.values import ScalarV as SV

        return SV(DataType.BOOL, self._host_fn(lv.value, rv.value))

    def do_columnar(self, ctx, lv, rv):
        assert isinstance(rv, ScalarV), f"{type(self).__name__} needs scalar needle"
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return getattr(S, self._device_fn)(ctx, lv, rv.value)
        f = self._host_fn
        return np.array([f(s, rv.value) for s in lv.data], dtype=bool)


class StartsWith(_NeedleOp):
    _host_fn = staticmethod(lambda s, n: s.startswith(n))
    _device_fn = "starts_with"


class EndsWith(_NeedleOp):
    _host_fn = staticmethod(lambda s, n: s.endswith(n))
    _device_fn = "ends_with"


class Contains(_NeedleOp):
    _host_fn = staticmethod(lambda s, n: n in s)
    _device_fn = "contains"


class Like(BinaryExpression):
    """SQL LIKE with the supported pattern subset (see
    columnar/strings.py:classify_like); the meta layer tags unsupported
    patterns for CPU fallback."""

    @property
    def data_type(self):
        return DataType.BOOL

    def eval_scalars(self, lv, rv):
        from spark_rapids_tpu.ops.values import ScalarV as SV

        return SV(DataType.BOOL, bool(_like_regex(rv.value).match(lv.value)))

    def do_columnar(self, ctx, lv, rv):
        assert isinstance(rv, ScalarV)
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.like_match(ctx, lv, rv.value)

        pat = _like_regex(rv.value)
        return np.array([bool(pat.match(s)) for s in lv.data], dtype=bool)


class StringTrim(UnaryExpression):
    _side = "both"

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.trim_spaces(ctx, v, self._side)
        fn = {"both": str.strip, "left": str.lstrip, "right": str.rstrip}[self._side]
        return _obj(lambda s: fn(s, " "), v.data)


class StringTrimLeft(StringTrim):
    _side = "left"


class StringTrimRight(StringTrim):
    _side = "right"


def _java_replacement_to_python(repl: str) -> str:
    """Translate a Java Matcher.replaceAll replacement to a python re
    template: $N -> \\g<N>, backslash-escaped char -> that literal char."""
    out = []
    i = 0
    n = len(repl)
    while i < n:
        ch = repl[i]
        if ch == "\\" and i + 1 < n:
            nxt = repl[i + 1]
            out.append("\\\\" if nxt == "\\" else nxt)
            i += 2
        elif ch == "$" and i + 1 < n and repl[i + 1].isdigit():
            j = i + 1
            while j < n and repl[j].isdigit():
                j += 1
            out.append(f"\\g<{repl[i + 1:j]}>")
            i = j
        elif ch == "\\":
            out.append("\\\\")  # trailing backslash: Java errors; keep literal
            i += 1
        else:
            out.append(ch)
            i += 1
    return "".join(out)


class _ScalarArgsTernary(TernaryExpression):
    """Ternary whose 2nd/3rd operands are scalar 'needle' arguments that
    must STAY scalars (the base TernaryExpression lifts string scalars to
    columns, which needle-style kernels can't use — same restriction as the
    reference's scalar-only cudf args, stringFunctions.scala)."""

    def eval_kernel(self, ctx, av, bv, cv):
        from spark_rapids_tpu.ops.base import (
            _fold_result,
            _lift_string_scalar,
            _null_string_col,
            _scalar_fold_ctx,
            and_validity,
            zero_nulls,
        )

        for v in (bv, cv):
            if not isinstance(v, ScalarV):
                raise TypeError(
                    f"{type(self).__name__} requires scalar arguments")
        if bv.is_null or cv.is_null or \
                (isinstance(av, ScalarV) and av.is_null):
            if self.data_type is DataType.STRING:
                return _null_string_col(ctx)
            return ColV(self.data_type,
                        ctx.xp.zeros((ctx.capacity,),
                                     dtype=self.data_type.to_np()),
                        ctx.xp.zeros((ctx.capacity,), dtype=bool))
        if isinstance(av, ScalarV):
            if ctx.is_device:
                av = _lift_string_scalar(ctx, av)
            else:
                fctx = _scalar_fold_ctx()
                lifted = ColV(DataType.STRING,
                              np.array([av.value], dtype=object),
                              np.array([True]))
                return _fold_result(self.data_type,
                                    self.do_columnar(fctx, lifted, bv, cv))
        data = self.do_columnar(ctx, av, bv, cv)
        validity = av.validity
        if validity is None:
            validity = ctx.xp.ones((ctx.capacity,), dtype=bool)
        if isinstance(data, ColV):
            return ColV(data.dtype, data.data,
                        and_validity(ctx.xp, data.validity, validity),
                        data.offsets)
        return ColV(self.data_type, zero_nulls(ctx.xp, data, validity),
                    validity)


class StringReplace(_ScalarArgsTernary):
    """replace(str, search, replacement) — scalar search/replacement only
    (reference: GpuStringReplace requires scalar args). Device kernel
    (columnar/strings.replace_literal) requires a non-empty, borderless (or
    single-char) search so matches cannot overlap; other searches are tagged
    for CPU fallback by the meta layer."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, sv, fv, rv):
        assert isinstance(fv, ScalarV) and isinstance(rv, ScalarV)
        if fv.value == "":
            # Spark: empty search leaves the string unchanged (python's
            # str.replace would interleave the replacement everywhere)
            return sv
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.replace_literal(ctx, sv, fv.value, rv.value)
        return _obj(lambda s: s.replace(fv.value, rv.value), sv.data)


class SubstringIndex(_ScalarArgsTernary):
    """substring_index(str, delim, count) — the part of str before the
    count-th delim occurrence (count > 0) / after the |count|-th from the
    end (count < 0) (reference: GpuSubstringIndex, stringFunctions.scala —
    scalar delim+count like the cudf version). Device kernel requires a
    length-1 or borderless delim so occurrence ranks match Java's
    non-overlapping scan; other delims are tagged for CPU fallback by the
    meta layer."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, sv, dv, cv):
        assert isinstance(dv, ScalarV) and isinstance(cv, ScalarV)
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.substring_index(ctx, sv, dv.value, int(cv.value))

        def sub(s):
            # Java UTF8String.subStringIndex scan semantics: occurrences
            # may OVERLAP (the scan advances one position, not delim
            # length) — str.split would miscount for self-overlapping
            # delims, exactly the inputs routed to this CPU path
            d, n = dv.value, int(cv.value)
            if n == 0 or d == "":
                return ""
            if n > 0:
                idx = -1
                for _ in range(n):
                    idx = s.find(d, idx + 1)
                    if idx == -1:
                        return s
                return s[:idx]
            bound = len(s)
            idx = -1
            for _ in range(-n):
                idx = s.rfind(d, 0, bound)
                if idx == -1:
                    return s
                bound = idx + len(d) - 1
            return s[idx + len(d):]

        return _obj(sub, sv.data)


class RegExpReplace(_ScalarArgsTernary):
    """regexp_replace(str, pattern, replacement). Device support mirrors the
    reference's restriction (GpuOverrides.scala:1458-1468 + the regexList at
    :334-337): the pattern must be a literal containing NO regex
    metacharacters — i.e. it is really a literal replace — otherwise the
    meta layer tags the expression for CPU fallback (where python `re` runs
    the full regex)."""

    # the reference's regexList (metacharacter blocklist) plus '+', which
    # that list omits but is just as much a quantifier as '*'
    REGEX_CHARS = ("\\", "\x00", "\t", "\n", "\r", "\f", "[", "]", "^", "&",
                   ".", "*", "+", "$", "?", "|", "(", ")", "{", "}", ":",
                   "!", "<=", ">")

    @classmethod
    def is_simple_pattern(cls, pattern: str) -> bool:
        return not any(ch in pattern for ch in cls.REGEX_CHARS)

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, sv, pv, rv):
        assert isinstance(pv, ScalarV) and isinstance(rv, ScalarV)
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.replace_literal(ctx, sv, pv.value, rv.value)
        import re

        pat = re.compile(pv.value)
        repl = rv.value
        if "$" in repl or "\\" in repl:
            # Java Matcher.replaceAll semantics (Spark): $N = group ref,
            # backslash escapes the next char to a literal. The meta layer
            # keeps such replacements OFF the device, so this only runs on
            # the CPU oracle.
            py_repl = _java_replacement_to_python(repl)
            return _obj(lambda s: pat.sub(py_repl, s), sv.data)
        return _obj(lambda s: pat.sub(lambda _m: repl, s), sv.data)


class StringLocate(_ScalarArgsTernary):
    """locate(substr, str, start) — 1-based character position, 0 if absent
    (reference: GpuStringLocate, stringFunctions.scala:62; scalar substr and
    start, like the cudf version). Internal child order is (str, substr,
    start) so the scalar-args template sees the column first."""

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, sv, nv, pv):
        assert isinstance(nv, ScalarV) and isinstance(pv, ScalarV)
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.locate(ctx, nv.value, sv, int(pv.value))

        start = int(pv.value)

        def loc(s):
            if start < 1:
                return 0
            if nv.value == "":
                return start if start <= len(s) + 1 else 0
            return s.find(nv.value, start - 1) + 1

        return np.fromiter((loc(s) for s in sv.data), dtype=np.int32,
                           count=len(sv.data))


class InitCap(UnaryExpression):
    """initcap: first letter of each space-separated word uppercased, rest
    lowercased (reference: GpuInitCap, stringFunctions.scala:399; ASCII-only
    on device, flagged incompat like upper/lower)."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.initcap_ascii(ctx, v)

        def cap_words(s):
            return " ".join(w[:1].upper() + w[1:].lower()
                            for w in s.split(" "))

        return _obj(cap_words, v.data)


class ConcatWs(Expression):
    """concat_ws(sep, c1, c2, ...): join non-null values with the separator;
    never NULL (reference: Spark semantics; the v0.1 plugin leaves concat_ws
    on CPU — here it runs on device via a static per-row piece table)."""

    def __init__(self, sep: str, children):
        self.sep = sep
        self._children = tuple(children)

    def children(self):
        return self._children

    def with_children(self, new_children):
        return ConcatWs(self.sep, new_children)

    @property
    def data_type(self):
        return DataType.STRING

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        from spark_rapids_tpu.columnar import strings as S
        from spark_rapids_tpu.ops.values import ScalarV as SV

        vals = []
        for c in self._children:
            r = c.eval(ctx)
            vals.append(r)
        if all(isinstance(v, SV) for v in vals):
            parts = [v.value for v in vals if not v.is_null]
            return SV(DataType.STRING, self.sep.join(parts))
        if ctx.is_device:
            from spark_rapids_tpu.ops.eval import _scalar_to_colv

            vals = [
                _scalar_to_colv(ctx, v, DataType.STRING)
                if isinstance(v, SV) else v for v in vals
            ]
        return S.concat_ws(ctx, self.sep, vals)

    def _fingerprint_extra(self):
        return f"ws:{self.sep!r};"

    def __repr__(self):
        return f"concat_ws({self.sep!r}, {', '.join(map(repr, self._children))})"
