"""String expressions (reference: stringFunctions.scala, 698 LoC — substring,
replace, trim family, starts/ends/contains, concat, like, upper/lower, length).

Device kernels live in columnar/strings.py; the CPU-oracle path here is
plain python string ops over object arrays.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import (
    BinaryExpression,
    Expression,
    TernaryExpression,
    UnaryExpression,
)
from spark_rapids_tpu.ops.values import ColV, ScalarV


def _obj(fn, *arrs):
    """Apply a python fn element-wise over object arrays."""
    return np.array([fn(*vals) for vals in zip(*arrs)], dtype=object)


def _like_regex(pattern: str):
    """Translate SQL LIKE to an anchored regex ( % -> .*, _ -> . )."""
    import re

    return re.compile(
        "^" + "".join(
            ".*" if c == "%" else "." if c == "_" else re.escape(c)
            for c in pattern
        ) + "$",
        re.DOTALL,
    )


class Length(UnaryExpression):
    """Character length (reference: GpuLength)."""

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.utf8_char_lengths(v).astype(np.int32)
        return np.array([len(s) for s in v.data], dtype=np.int32)


class Upper(UnaryExpression):
    """Uppercase; device kernel is ASCII-only (non-ASCII bytes pass through),
    flagged incompat like the reference's locale-sensitive ops."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.upper_ascii(v)
        return _obj(lambda s: s.upper(), v.data)


class Lower(UnaryExpression):
    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.lower_ascii(v)
        return _obj(lambda s: s.lower(), v.data)


class Substring(TernaryExpression):
    """substring(str, pos, len) — 1-based, negative pos from end."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, sv, pv, lv):
        from spark_rapids_tpu.ops.base import _d

        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.substring_utf8(ctx, sv, _d(pv), _d(lv))

        def sub(s, p, ln):
            p, ln = int(p), int(ln)
            if ln < 0:
                ln = 0
            if p > 0:
                start = p - 1
            elif p < 0:
                start = max(len(s) + p, 0)
            else:
                start = 0
            return s[start:start + ln]

        pos = pv.data if isinstance(pv, ColV) else np.full(ctx.capacity, pv.value)
        ln = lv.data if isinstance(lv, ColV) else np.full(ctx.capacity, lv.value)
        return _obj(sub, sv.data, pos, ln)


class Concat(BinaryExpression):
    """concat(a, b); Spark concat is variadic — the planner folds n-ary concat
    into a left-deep chain of these."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, lv, rv):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.concat2(ctx, lv, rv)

        def side(v):
            if isinstance(v, ScalarV):
                return [v.value] * ctx.capacity
            return v.data

        return _obj(lambda a, b: a + b, side(lv), side(rv))


class _NeedleOp(BinaryExpression):
    """Base for StartsWith/EndsWith/Contains: right side must be a foldable
    string literal (same restriction as the reference, which requires scalar
    needles for cudf ops)."""

    _host_fn = None
    _device_fn = None

    @property
    def data_type(self):
        return DataType.BOOL

    def eval_scalars(self, lv, rv):
        from spark_rapids_tpu.ops.values import ScalarV as SV

        return SV(DataType.BOOL, self._host_fn(lv.value, rv.value))

    def do_columnar(self, ctx, lv, rv):
        assert isinstance(rv, ScalarV), f"{type(self).__name__} needs scalar needle"
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return getattr(S, self._device_fn)(ctx, lv, rv.value)
        f = self._host_fn
        return np.array([f(s, rv.value) for s in lv.data], dtype=bool)


class StartsWith(_NeedleOp):
    _host_fn = staticmethod(lambda s, n: s.startswith(n))
    _device_fn = "starts_with"


class EndsWith(_NeedleOp):
    _host_fn = staticmethod(lambda s, n: s.endswith(n))
    _device_fn = "ends_with"


class Contains(_NeedleOp):
    _host_fn = staticmethod(lambda s, n: n in s)
    _device_fn = "contains"


class Like(BinaryExpression):
    """SQL LIKE with the supported pattern subset (see
    columnar/strings.py:classify_like); the meta layer tags unsupported
    patterns for CPU fallback."""

    @property
    def data_type(self):
        return DataType.BOOL

    def eval_scalars(self, lv, rv):
        from spark_rapids_tpu.ops.values import ScalarV as SV

        return SV(DataType.BOOL, bool(_like_regex(rv.value).match(lv.value)))

    def do_columnar(self, ctx, lv, rv):
        assert isinstance(rv, ScalarV)
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.like_match(ctx, lv, rv.value)

        pat = _like_regex(rv.value)
        return np.array([bool(pat.match(s)) for s in lv.data], dtype=bool)


class StringTrim(UnaryExpression):
    _side = "both"

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, v):
        if ctx.is_device:
            from spark_rapids_tpu.columnar import strings as S

            return S.trim_spaces(ctx, v, self._side)
        fn = {"both": str.strip, "left": str.lstrip, "right": str.rstrip}[self._side]
        return _obj(lambda s: fn(s, " "), v.data)


class StringTrimLeft(StringTrim):
    _side = "left"


class StringTrimRight(StringTrim):
    _side = "right"


class StringReplace(TernaryExpression):
    """replace(str, search, replacement) — scalar search/replacement only
    (reference: GpuStringReplace requires scalar args). Device path currently
    tags for fallback when replacement length differs unpredictably; the
    simple equal/shrink case runs on device via contains/substring composition
    in a later round, so for now the meta layer marks this CPU-only on device
    unless search == '' (identity)."""

    @property
    def data_type(self):
        return DataType.STRING

    def do_columnar(self, ctx, sv, fv, rv):
        assert isinstance(fv, ScalarV) and isinstance(rv, ScalarV)
        if ctx.is_device:
            raise NotImplementedError("StringReplace device kernel (round 2)")
        return _obj(lambda s: s.replace(fv.value, rv.value), sv.data)
