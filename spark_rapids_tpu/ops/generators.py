"""Generator expressions: array construction + explode/posexplode markers.

Reference parity: the v0.1 Generate support is explode/posexplode of a
CREATED array — GpuGenerateExec handles `Explode(CreateArray(exprs))` /
`PosExplode(CreateArray(exprs))` and literal arrays, rejecting everything
else (GpuGenerateExec.scala tagPlanForGpu: "Only posexplode of a created
array is currently supported"; `outer` unsupported). There is no ARRAY
column type in the engine (flat types only, GpuOverrides.scala:383-395), so
`CreateArray` never evaluates: the planner pattern-matches
Explode(CreateArray(...)) in DataFrame.select and lowers it to a Generate
plan that projects each element expression per row (the reference's
table-replication trick).
"""

from __future__ import annotations

from typing import Sequence

from spark_rapids_tpu.columnar.dtypes import DataType, common_type
from spark_rapids_tpu.ops.base import Expression


class CreateArray(Expression):
    """array(e1, e2, ...) — consumable only by Explode/PosExplode."""

    def __init__(self, elems: Sequence[Expression]):
        if not elems:
            raise ValueError("array() requires at least one element")
        self.elems = tuple(elems)

    def children(self):
        return self.elems

    def with_children(self, new_children):
        return CreateArray(new_children)

    @property
    def element_type(self) -> DataType:
        t = self.elems[0].data_type
        for e in self.elems[1:]:
            nt = e.data_type
            if nt is DataType.NULL:
                continue
            if t is DataType.NULL:
                t = nt
                continue
            c = common_type(t, nt)
            if c is None and t is not nt:
                raise TypeError(
                    f"array elements have incompatible types {t} and {nt}")
            t = c or t
        return t

    @property
    def data_type(self) -> DataType:
        # arrays are not a columnar type here; exposed for tagging messages
        return self.element_type

    def eval(self, ctx):
        raise NotImplementedError(
            "CreateArray only appears under explode()/posexplode()")

    def _fingerprint_extra(self):
        return "createarray;"

    def __repr__(self):
        return f"array({', '.join(map(repr, self.elems))})"


class Explode(Expression):
    """explode(array(...)): one output row per element per input row
    (reference: GpuGenerateExec with includePos=false)."""

    include_pos = False

    def __init__(self, child: CreateArray):
        self.array = child

    def children(self):
        return (self.array,)

    def with_children(self, new_children):
        return type(self)(new_children[0])

    @property
    def data_type(self) -> DataType:
        return self.array.element_type

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, ctx):
        raise NotImplementedError(
            "explode() must be planned as a Generate node (DataFrame.select)")

    def __repr__(self):
        return f"explode({self.array!r})"


class PosExplode(Explode):
    """posexplode(array(...)): adds the element position column
    (reference: GpuGenerateExec with includePos=true)."""

    include_pos = True

    def __repr__(self):
        return f"posexplode({self.array!r})"
