"""Null-handling expressions (reference: nullExpressions.scala, 297 LoC —
coalesce, isnull/isnotnull, isnan, nanvl, AtLeastNNonNulls)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import (
    BinaryExpression,
    Expression,
    UnaryExpression,
    _d,
)
from spark_rapids_tpu.ops.values import ColV, ScalarV, broadcast_scalar


class IsNull(UnaryExpression):
    @property
    def data_type(self):
        return DataType.BOOL

    @property
    def nullable(self):
        return False

    def eval_kernel(self, ctx, v):
        xp = ctx.xp
        if isinstance(v, ScalarV):
            return ScalarV(DataType.BOOL, v.is_null)
        data = ~v.validity
        validity = xp.ones((ctx.capacity,), dtype=bool)
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = data & validity
        return ColV(DataType.BOOL, data, validity)


class IsNotNull(UnaryExpression):
    @property
    def data_type(self):
        return DataType.BOOL

    @property
    def nullable(self):
        return False

    def eval_kernel(self, ctx, v):
        xp = ctx.xp
        if isinstance(v, ScalarV):
            return ScalarV(DataType.BOOL, not v.is_null)
        validity = xp.ones((ctx.capacity,), dtype=bool)
        if ctx.is_device:
            validity = validity & ctx.row_mask()
        return ColV(DataType.BOOL, v.validity & validity, validity)


class IsNan(UnaryExpression):
    @property
    def data_type(self):
        return DataType.BOOL

    @property
    def nullable(self):
        return False

    def eval_kernel(self, ctx, v):
        xp = ctx.xp
        if isinstance(v, ScalarV):
            return ScalarV(DataType.BOOL,
                           v.value is not None and np.isnan(v.value))
        data = xp.isnan(v.data) & v.validity
        validity = xp.ones((ctx.capacity,), dtype=bool)
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = data & validity
        return ColV(DataType.BOOL, data, validity)


class NaNvl(BinaryExpression):
    """nanvl(a, b): b where a is NaN else a."""

    @property
    def data_type(self):
        return self.left.data_type

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        l, r = _d(lv), _d(rv)
        return xp.where(xp.isnan(l), r, l)


class Coalesce(Expression):
    def __init__(self, *exprs: Expression):
        assert exprs
        self.exprs = tuple(exprs)

    def children(self):
        return self.exprs

    def with_children(self, new_children):
        return Coalesce(*new_children)

    @property
    def data_type(self):
        return self.exprs[0].data_type

    @property
    def nullable(self):
        return all(e.nullable for e in self.exprs)

    def eval_kernel(self, ctx, *vals):
        xp = ctx.xp
        if all(isinstance(v, ScalarV) for v in vals):
            for v in vals:
                if not v.is_null:
                    return ScalarV(self.data_type, v.value)
            return ScalarV(self.data_type, None)
        if self.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            return S.string_coalesce(ctx, vals)
        cols = [broadcast_scalar(ctx, v) if isinstance(v, ScalarV) else v
                for v in vals]
        data = cols[-1].data
        validity = cols[-1].validity
        for c in reversed(cols[:-1]):
            data = xp.where(c.validity, c.data, data)
            validity = c.validity | validity
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = xp.where(validity, data, 0)
        vrange = None
        if self.data_type.is_integral:
            from spark_rapids_tpu.columnar.batch import union_vrange
            from spark_rapids_tpu.ops.base import val_interval

            vrange = union_vrange(*[val_interval(v) for v in vals])
        return ColV(self.data_type, data, validity, vrange=vrange)


class AtLeastNNonNulls(Expression):
    def __init__(self, n: int, *exprs: Expression):
        self.n = n
        self.exprs = tuple(exprs)

    def children(self):
        return self.exprs

    def with_children(self, new_children):
        return AtLeastNNonNulls(self.n, *new_children)

    @property
    def data_type(self):
        return DataType.BOOL

    @property
    def nullable(self):
        return False

    def eval_kernel(self, ctx, *vals):
        xp = ctx.xp
        count = xp.zeros((ctx.capacity,), dtype=np.int32)
        for v in vals:
            if isinstance(v, ScalarV):
                if not v.is_null:
                    count = count + 1
            else:
                valid = v.validity
                if v.dtype.is_floating:
                    valid = valid & ~xp.isnan(v.data)
                count = count + valid.astype(np.int32)
        data = count >= self.n
        validity = xp.ones((ctx.capacity,), dtype=bool)
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = data & validity
        return ColV(DataType.BOOL, data, validity)

    def _fingerprint_extra(self):
        return f"{self.n};"
