"""Literals (reference: literals.scala — GpuLiteral :120, GpuScalar.from :33)."""

from __future__ import annotations

from typing import Any, Optional

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import LeafExpression
from spark_rapids_tpu.ops.values import ScalarV


def infer_literal_type(value: Any):
    import decimal as _dec

    if isinstance(value, bool):
        return DataType.BOOL
    if isinstance(value, int):
        return DataType.INT32 if -(2**31) <= value < 2**31 else DataType.INT64
    if isinstance(value, float):
        return DataType.FLOAT64
    if isinstance(value, str):
        return DataType.STRING
    if isinstance(value, _dec.Decimal):
        from spark_rapids_tpu.ops.decimal_util import infer_decimal_type

        return infer_decimal_type(value)
    raise TypeError(f"cannot infer literal type for {value!r}")


class Literal(LeafExpression):
    def __init__(self, value: Any, dtype: Optional[DataType] = None):
        if dtype is None:
            dtype = DataType.NULL if value is None else infer_literal_type(value)
        if getattr(dtype, "is_decimal", False) and value is not None:
            from spark_rapids_tpu.ops.decimal_util import to_unscaled

            # values are LOGICAL (5 means 5.00, like createDataFrame input);
            # stored physically as the unscaled int64, collect converts back
            value = to_unscaled(value, dtype.scale, dtype.precision)
        self.value = value
        self._dtype = dtype

    @property
    def data_type(self):
        return self._dtype

    @property
    def nullable(self):
        return self.value is None

    @property
    def foldable(self):
        return True

    @property
    def deterministic(self):
        return True

    def eval(self, ctx):
        return ScalarV(self._dtype, self.value)

    def eval_kernel(self, ctx):
        return ScalarV(self._dtype, self.value)

    def _fingerprint_extra(self):
        return f"{self.value!r}:{self._dtype.name};"

    def __repr__(self):
        return f"lit({self.value!r})"


def lit(value: Any, dtype: Optional[DataType] = None) -> Literal:
    return Literal(value, dtype)
