"""Column hashing for hash partitioning and key grouping.

Reference parity: GpuHashPartitioning.scala computes a cudf murmur3 hash that
is bit-compatible with Spark's CPU Murmur3Hash so CPU and GPU stages can
co-partition. This framework owns BOTH engines (numpy oracle + TPU), so the
requirement degrades to *internal* consistency: the same engine must hash
equal keys equally. We implement a murmur3-style finalizer-based mix that is
identical across the numpy and jnp paths (same uint32 arithmetic), so even
mixed CPU/TPU plans co-partition.

All arithmetic is uint32 with wraparound, expressible identically in numpy
and jax.numpy. Strings hash via a 31/1000003 double polynomial accumulated
bytewise on the device representation (offsets+bytes) using a
searchsorted-based byte->row map, and via Python bytes on the host path.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.values import ColV

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_GOLDEN = np.uint32(0x9E3779B9)

HASH_SEED = np.uint32(42)  # Spark's default seed (reference: Murmur3Hash)


def _rotl32(xp, x, r: int):
    x = x.astype(np.uint32) if hasattr(x, "astype") else np.uint32(x)
    return ((x << np.uint32(r)) | (x >> np.uint32(32 - r))).astype(np.uint32)


def _fmix32(xp, h):
    h = h.astype(np.uint32)
    h = h ^ (h >> np.uint32(16))
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h = h ^ (h >> np.uint32(13))
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h = h ^ (h >> np.uint32(16))
    return h


def _mix_k1(xp, k1):
    k1 = (k1.astype(np.uint32) * _C1).astype(np.uint32)
    k1 = _rotl32(xp, k1, 15)
    return (k1 * _C2).astype(np.uint32)


def _mix_h1(xp, h1, k1):
    h1 = h1 ^ _mix_k1(xp, k1)
    h1 = _rotl32(xp, h1, 13)
    return (h1.astype(np.uint32) * np.uint32(5) + np.uint32(0xE6546B64)).astype(
        np.uint32)


def _as_u32(xp, arr):
    """Reinterpret/convert an integer array to uint32 words (low 32 bits)."""
    return (arr.astype(np.int64) & np.int64(0xFFFFFFFF)).astype(np.uint32)


def _canonical_float_bits(xp, data, dtype: DataType):
    """f32 bit pattern with -0.0 -> +0.0 and all NaNs canonical, widened/
    narrowed from the physical dtype. f64 on the oracle path hashes by its
    f32-narrowed value so CPU and TPU co-partition DOUBLE keys."""
    f32 = data.astype(np.float32)
    f32 = xp.where(f32 == 0.0, xp.zeros((), np.float32), f32)  # -0.0 -> 0.0
    nan = xp.isnan(f32)
    bits = f32.view(np.uint32)
    canonical_nan = np.uint32(0x7FC00000)
    return xp.where(nan, canonical_nan, bits).astype(np.uint32)


def column_words(xp, col: ColV) -> List[Any]:
    """Decompose a (non-string) column into a list of uint32 word arrays.
    Null rows contribute the word 0 (data is zeroed at nulls by convention,
    and the null flag is mixed separately by hash_columns)."""
    dt = col.dtype
    data = col.data
    if dt is DataType.BOOL:
        return [data.astype(np.uint32)]
    if dt in (DataType.INT8, DataType.INT16, DataType.INT32, DataType.DATE):
        # sign-extend to i64 then take low word, exactly like casting to int
        return [_as_u32(xp, data.astype(np.int64))]
    if dt in (DataType.INT64, DataType.TIMESTAMP) or \
            getattr(dt, "is_decimal", False):
        # decimals hash their unscaled int64 exactly like LONG columns
        x = data.astype(np.int64)
        lo = _as_u32(xp, x)
        hi = _as_u32(xp, x >> np.int64(32))
        return [lo, hi]
    if dt in (DataType.FLOAT32, DataType.FLOAT64):
        return [_canonical_float_bits(xp, data, dt)]
    raise TypeError(f"cannot hash column of type {dt}")


def _string_words_host(col: ColV) -> List[Any]:
    """Host path: per-row double polynomial over utf-8 bytes."""
    n = len(col.data)
    h1 = np.zeros(n, dtype=np.uint32)
    h2 = np.zeros(n, dtype=np.uint32)
    lens = np.zeros(n, dtype=np.uint32)
    for i, s in enumerate(col.data):
        b = s.encode("utf-8") if isinstance(s, str) else bytes(s)
        a1 = 0
        a2 = 0
        for byte in b:
            a1 = (a1 * 31 + byte) & 0xFFFFFFFF
            a2 = (a2 * 1000003 + byte) & 0xFFFFFFFF
        h1[i], h2[i], lens[i] = a1, a2, len(b)
    return [h1, h2, lens]


def _string_words_device(col: ColV) -> List[Any]:
    """Device path: the same double polynomial, computed byte-centrically.

    For byte position p belonging to row r at in-row offset k (k counted from
    the string START), the poly-31 contribution is byte * 31^(len-1-k).
    Accumulate with a segment-sum over rows. 31^m is computed mod 2^32 via
    repeated-squaring on the exponent's bits (m <= 2^31).
    """
    import jax
    import jax.numpy as jnp

    offsets = col.offsets
    nrows = offsets.shape[0] - 1
    data = col.data
    nbytes = data.shape[0]
    pos = jnp.arange(nbytes, dtype=jnp.int32)
    row = jnp.searchsorted(offsets[1:], pos, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, nrows - 1)
    start = offsets[row]
    end = offsets[row + 1]
    in_str = (pos >= start) & (pos < end)
    k = pos - start
    m = (end - start - 1 - k).astype(jnp.uint32)
    contrib1 = data.astype(jnp.uint32) * _pow_mod32(jnp, jnp.uint32(31), m)
    contrib2 = data.astype(jnp.uint32) * _pow_mod32(jnp, jnp.uint32(1000003), m)
    seg = jnp.where(in_str, row, nrows)
    h1 = jax.ops.segment_sum(jnp.where(in_str, contrib1, 0), seg,
                             num_segments=nrows).astype(jnp.uint32)
    h2 = jax.ops.segment_sum(jnp.where(in_str, contrib2, 0), seg,
                             num_segments=nrows).astype(jnp.uint32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.uint32)
    valid = col.validity
    z = jnp.zeros((), jnp.uint32)
    return [jnp.where(valid, h1, z), jnp.where(valid, h2, z),
            jnp.where(valid, lens, z)]


def _pow_mod32(xp, base, exp_u32):
    """base^exp mod 2^32 elementwise, via square-and-multiply over 32 bits."""
    result = xp.ones_like(exp_u32, dtype=np.uint32)
    b = xp.full_like(exp_u32, base, dtype=np.uint32)
    e = exp_u32
    for _ in range(32):
        bit = (e & np.uint32(1)).astype(bool)
        result = xp.where(bit, (result * b).astype(np.uint32), result)
        b = (b * b).astype(np.uint32)
        e = e >> np.uint32(1)
    return result


def string_words(xp, col: ColV) -> List[Any]:
    if col.offsets is None and isinstance(col.data, np.ndarray) and \
            col.data.dtype == object:
        return _string_words_host(col)
    return _string_words_device(col)


def matrix_string_words(xp, mat, lens, validity) -> List[Any]:
    """String hash words from a fixed-width [rows, W] byte matrix + per-row
    byte lengths — bit-identical to _string_words_device on the
    (offsets, bytes) representation, for rows exchanged as padded
    fixed-width buckets (shuffle/ici.py). Bytes at j >= len are ignored."""
    import jax.numpy as jnp

    W = mat.shape[1]
    j = jnp.arange(W, dtype=jnp.int32)[None, :]
    lens_i = lens.astype(jnp.int32)[:, None]
    in_str = j < lens_i
    m = jnp.where(in_str, lens_i - 1 - j, 0).astype(jnp.uint32)
    u = mat.astype(jnp.uint32)
    c1 = u * _pow_mod32(jnp, jnp.uint32(31), m)
    c2 = u * _pow_mod32(jnp, jnp.uint32(1000003), m)
    zero = jnp.zeros((), jnp.uint32)
    h1 = jnp.sum(jnp.where(in_str, c1, zero), axis=1).astype(jnp.uint32)
    h2 = jnp.sum(jnp.where(in_str, c2, zero), axis=1).astype(jnp.uint32)
    lens_u = lens.astype(jnp.uint32)
    return [jnp.where(validity, h1, zero), jnp.where(validity, h2, zero),
            jnp.where(validity, lens_u, zero)]


def hash_word_entries(xp, entries, seed=HASH_SEED):
    """Murmur3-style mix over pre-decomposed (words, validity) entries."""
    h: Optional[Any] = None
    for words, validity in entries:
        nullw = xp.where(validity, np.uint32(0), _GOLDEN).astype(np.uint32)
        # zero data words at null lanes: an evaluated column may carry
        # arbitrary data under null, and all NULLs must hash identically
        words = [xp.where(validity, w, np.uint32(0)).astype(np.uint32)
                 for w in words] + [nullw]
        for w in words:
            if h is None:
                h = xp.full(w.shape, np.uint32(seed), dtype=np.uint32)
            h = _mix_h1(xp, h, w.astype(np.uint32))
    assert h is not None, "hash needs at least one column"
    return _fmix32(xp, h)


def hash_columns(xp, cols: List[ColV], seed=HASH_SEED):
    """Murmur3-style row hash over multiple columns -> uint32 array.

    Nulls: the reference's Spark semantics skip null columns entirely (hash of
    null = seed passthrough); we mix an explicit null flag word instead, which
    is simpler and equally consistent for partitioning/grouping since both
    engines here share this code path.
    """
    entries = [(string_words(xp, col) if col.dtype is DataType.STRING
                else column_words(xp, col), col.validity) for col in cols]
    return hash_word_entries(xp, entries, seed)


def partition_ids(xp, cols: List[ColV], num_partitions: int):
    """pmod(hash, n) partition index per row -> int32 in [0, n)."""
    h = hash_columns(xp, cols)
    return (h % np.uint32(num_partitions)).astype(np.int32)


def partition_ids_from_entries(xp, entries, num_partitions: int):
    """partition_ids over pre-decomposed (words, validity) entries."""
    h = hash_word_entries(xp, entries)
    return (h % np.uint32(num_partitions)).astype(np.int32)
