"""Declarative aggregate functions (reference: AggregateFunctions.scala, 533 LoC).

The reference models every aggregate as cudf update/merge aggregate pairs plus
expression trees for initial values and final evaluation
(AggregateFunctions.scala:171-533). This shape is exactly what makes
partial/final aggregation composable across a shuffle, so it is kept:

- `update_aggs`: (buffer_name, reduce_op, input_expr) applied to raw input
  batches in Partial mode;
- `merge_aggs`:  (buffer_name, reduce_op) applied to partial buffers in
  Final mode;
- `evaluate_expression`: expression over buffer attributes producing the
  result column;
- `default_values`: result for an empty ungrouped reduction
  (reference: aggregate.scala:406-419).

The reduce ops are names understood by the exec layer's segmented-reduce
kernel (exec/aggregate.py): sum / min / max / count / first / last / any.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import (
    Alias,
    AttributeReference,
    Expression,
    UnaryExpression,
)
from spark_rapids_tpu.ops.literals import Literal

# (buffer name suffix, reduce op, source expression)
UpdateAgg = Tuple[str, str, Expression]
MergeAgg = Tuple[str, str]


class AggregateFunction(Expression):
    """Base marker; not directly evaluable (evaluation happens through the
    buffer machinery in the aggregate exec)."""

    def __init__(self, child: Expression):
        self.child = child
        self._id = None

    def children(self):
        return (self.child,)

    def with_children(self, new_children):
        return type(self)(*new_children)

    @property
    def nullable(self):
        return True

    # -- declarative pieces --------------------------------------------------
    def buffer_attrs(self) -> List[AttributeReference]:
        raise NotImplementedError

    def update_aggs(self) -> List[UpdateAgg]:
        raise NotImplementedError

    def merge_aggs(self) -> List[MergeAgg]:
        raise NotImplementedError

    def evaluate_expression(self, buffers: List[AttributeReference]) -> Expression:
        raise NotImplementedError

    def default_value(self):
        """Result value for empty ungrouped reduction (None = SQL NULL)."""
        return None

    def initial_buffer_values(self) -> List:
        """Buffer values for the empty ungrouped reduction (the reference's
        initialValues expression trees, AggregateFunctions.scala:253-533).
        One entry per buffer attr; None = SQL NULL."""
        return [None] * len(self.buffer_attrs())

    def eval_kernel(self, ctx, *vals):
        raise RuntimeError("aggregate functions evaluate via the agg exec")


class Min(AggregateFunction):
    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("min", self.data_type, True)]

    def update_aggs(self):
        return [("min", "min", self.child)]

    def merge_aggs(self):
        return [("min", "min")]

    def evaluate_expression(self, buffers):
        return buffers[0]


class Max(AggregateFunction):
    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("max", self.data_type, True)]

    def update_aggs(self):
        return [("max", "max", self.child)]

    def merge_aggs(self):
        return [("max", "max")]

    def evaluate_expression(self, buffers):
        return buffers[0]


def _sum_type(dt: DataType) -> DataType:
    if dt in (DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64):
        return DataType.INT64
    return DataType.FLOAT64


class Sum(AggregateFunction):
    @property
    def data_type(self):
        return _sum_type(self.child.data_type)

    def buffer_attrs(self):
        return [AttributeReference("sum", self.data_type, True)]

    def update_aggs(self):
        from spark_rapids_tpu.ops.cast import Cast

        src = self.child
        if src.data_type != self.data_type:
            src = Cast(src, self.data_type)
        return [("sum", "sum", src)]

    def merge_aggs(self):
        return [("sum", "sum")]

    def evaluate_expression(self, buffers):
        return buffers[0]


class Count(AggregateFunction):
    """count(expr) — counts non-null; count(*) is Count(Literal(1))."""

    @property
    def data_type(self):
        return DataType.INT64

    @property
    def nullable(self):
        return False

    def buffer_attrs(self):
        return [AttributeReference("count", DataType.INT64, False)]

    def update_aggs(self):
        return [("count", "count", self.child)]

    def merge_aggs(self):
        return [("count", "sum")]

    def evaluate_expression(self, buffers):
        return buffers[0]

    def default_value(self):
        return 0

    def initial_buffer_values(self):
        return [0]


class Average(AggregateFunction):
    @property
    def data_type(self):
        return DataType.FLOAT64

    def buffer_attrs(self):
        return [
            AttributeReference("sum", DataType.FLOAT64, True),
            AttributeReference("count", DataType.INT64, False),
        ]

    def update_aggs(self):
        from spark_rapids_tpu.ops.cast import Cast

        src = self.child
        if src.data_type is not DataType.FLOAT64:
            src = Cast(src, DataType.FLOAT64)
        return [("sum", "sum", src), ("count", "count", self.child)]

    def merge_aggs(self):
        return [("sum", "sum"), ("count", "sum")]

    def evaluate_expression(self, buffers):
        from spark_rapids_tpu.ops.arithmetic import Divide
        from spark_rapids_tpu.ops.cast import Cast

        return Divide(buffers[0], Cast(buffers[1], DataType.FLOAT64))

    def initial_buffer_values(self):
        return [None, 0]


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, new_children):
        return First(new_children[0], self.ignore_nulls)

    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("first", self.data_type, True)]

    def update_aggs(self):
        op = "first_ignore_nulls" if self.ignore_nulls else "first"
        return [("first", op, self.child)]

    def merge_aggs(self):
        op = "first_ignore_nulls" if self.ignore_nulls else "first"
        return [("first", op)]

    def evaluate_expression(self, buffers):
        return buffers[0]

    def _fingerprint_extra(self):
        return f"{self.ignore_nulls};"


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, new_children):
        return Last(new_children[0], self.ignore_nulls)

    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("last", self.data_type, True)]

    def update_aggs(self):
        op = "last_ignore_nulls" if self.ignore_nulls else "last"
        return [("last", op, self.child)]

    def merge_aggs(self):
        op = "last_ignore_nulls" if self.ignore_nulls else "last"
        return [("last", op)]

    def evaluate_expression(self, buffers):
        return buffers[0]

    def _fingerprint_extra(self):
        return f"{self.ignore_nulls};"
