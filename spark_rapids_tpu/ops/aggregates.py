"""Declarative aggregate functions (reference: AggregateFunctions.scala, 533 LoC).

The reference models every aggregate as cudf update/merge aggregate pairs plus
expression trees for initial values and final evaluation
(AggregateFunctions.scala:171-533). This shape is exactly what makes
partial/final aggregation composable across a shuffle, so it is kept:

- `update_aggs`: (buffer_name, reduce_op, input_expr) applied to raw input
  batches in Partial mode;
- `merge_aggs`:  (buffer_name, reduce_op) applied to partial buffers in
  Final mode;
- `evaluate_expression`: expression over buffer attributes producing the
  result column;
- `default_values`: result for an empty ungrouped reduction
  (reference: aggregate.scala:406-419).

The reduce ops are names understood by the exec layer's segmented-reduce
kernel (exec/aggregate.py): sum / min / max / count / first / last / any.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import (
    Alias,
    AttributeReference,
    BinaryExpression,
    Expression,
    TernaryExpression,
    UnaryExpression,
)
from spark_rapids_tpu.ops.literals import Literal

# (buffer name suffix, reduce op, source expression)
UpdateAgg = Tuple[str, str, Expression]
MergeAgg = Tuple[str, str]


class AggregateFunction(Expression):
    """Base marker; not directly evaluable (evaluation happens through the
    buffer machinery in the aggregate exec)."""

    def __init__(self, child: Expression):
        self.child = child
        self._id = None

    def children(self):
        return (self.child,)

    def with_children(self, new_children):
        return type(self)(*new_children)

    @property
    def nullable(self):
        return True

    # -- declarative pieces --------------------------------------------------
    def buffer_attrs(self) -> List[AttributeReference]:
        raise NotImplementedError

    def update_aggs(self) -> List[UpdateAgg]:
        raise NotImplementedError

    def merge_aggs(self) -> List[MergeAgg]:
        raise NotImplementedError

    def evaluate_expression(self, buffers: List[AttributeReference]) -> Expression:
        raise NotImplementedError

    def default_value(self):
        """Result value for empty ungrouped reduction (None = SQL NULL)."""
        return None

    def initial_buffer_values(self) -> List:
        """Buffer values for the empty ungrouped reduction (the reference's
        initialValues expression trees, AggregateFunctions.scala:253-533).
        One entry per buffer attr; None = SQL NULL."""
        return [None] * len(self.buffer_attrs())

    def eval_kernel(self, ctx, *vals):
        raise RuntimeError("aggregate functions evaluate via the agg exec")


class Min(AggregateFunction):
    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("min", self.data_type, True)]

    def update_aggs(self):
        return [("min", "min", self.child)]

    def merge_aggs(self):
        return [("min", "min")]

    def evaluate_expression(self, buffers):
        return buffers[0]


class Max(AggregateFunction):
    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("max", self.data_type, True)]

    def update_aggs(self):
        return [("max", "max", self.child)]

    def merge_aggs(self):
        return [("max", "max")]

    def evaluate_expression(self, buffers):
        return buffers[0]


def _sum_type(dt):
    if getattr(dt, "is_decimal", False):
        # Spark: sum(decimal(p,s)) -> decimal(p+10, s), capped at the 64-bit
        # MAX_PRECISION (sums beyond 18 digits are out of 64-bit range)
        from spark_rapids_tpu.columnar.dtypes import DecimalType

        return DecimalType(min(dt.precision + 10, DecimalType.MAX_PRECISION),
                           dt.scale)
    if dt in (DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64):
        return DataType.INT64
    return DataType.FLOAT64


class _UnscaledHi(UnaryExpression):
    """High 32 bits (arithmetic shift) of a decimal's unscaled int64."""

    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        return v.data.astype(np.int64) >> np.int64(32)


class _UnscaledLo(UnaryExpression):
    """Low 32 bits (non-negative) of a decimal's unscaled int64."""

    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        return v.data.astype(np.int64) & np.int64(0xFFFFFFFF)


class _UnscaledRaw(UnaryExpression):
    """A decimal's unscaled int64 value itself (no split)."""

    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        return v.data.astype(np.int64)


def _narrow_decimal(dt) -> bool:
    """precision <= 9 bounds |unscaled| <= 10^9-1 < 2^31: ONE int64
    segment-sum is then exact below 2^32 rows per group (|sum| < 2^31 * n
    < 2^63), so the hi/lo overflow-detection split — and its second
    reduction — is unnecessary. Half the device reduction work for the
    common small-precision columns (every TPCx-BB money column)."""
    return dt.precision <= 9


class _NarrowDecimalSumFinish(BinaryExpression):
    """Finish a narrow-decimal sum: (sum, count) -> decimal. NULL when the
    per-group count reaches 2^32 (the one point the single int64 partial
    could have wrapped undetectably) or the true sum overflows the result
    precision — same "NULL, never a wrong value" contract as
    _DecimalSumFinish."""

    def __init__(self, s, n, result_type):
        super().__init__(s, n)
        self._result_type = result_type

    def with_children(self, new_children):
        return _NarrowDecimalSumFinish(new_children[0], new_children[1],
                                       self._result_type)

    @property
    def data_type(self):
        return self._result_type

    @property
    def nullable(self):
        return True

    def _fingerprint_extra(self):
        return f"{self._result_type.name};"

    def do_columnar(self, ctx, lv, nv):
        from spark_rapids_tpu.ops import decimal_util as DU
        from spark_rapids_tpu.ops.base import _d
        from spark_rapids_tpu.ops.values import ColV

        xp = ctx.xp
        s = DU._i64(xp, _d(lv))
        n = DU._i64(xp, _d(nv))
        exact = n < np.int64(2 ** 32)
        val, ok2 = DU.fit_precision(xp, s, self._result_type.precision)
        ok = exact & ok2
        return ColV(self._result_type, xp.where(ok, val, 0), ok)


class _DecimalSumFinish(TernaryExpression):
    """Recombine hi/lo partial sums into the final decimal sum.

    The hi/lo split makes 64-bit decimal sums *exact*: per-lane
    v == (v >> 32)*2^32 + (v & 0xffffffff), and neither partial sum can wrap
    int64 for any group under 2^31 rows.  The third operand is the per-group
    non-null row count; at or above 2^31 rows the lo partial itself could
    have wrapped undetectably, so the result is NULL (the framework's
    "NULL, never a wrong value" guarantee — Spark would keep summing, but a
    silently wrapped value is worse than a conservative NULL).  Overflow of
    the true sum beyond the result precision (or int64) likewise yields SQL
    NULL, matching Spark's non-ANSI decimal sum."""

    def __init__(self, hi, lo, n, result_type):
        super().__init__(hi, lo, n)
        self._result_type = result_type

    def with_children(self, new_children):
        return _DecimalSumFinish(new_children[0], new_children[1],
                                 new_children[2], self._result_type)

    @property
    def data_type(self):
        return self._result_type

    @property
    def nullable(self):
        return True

    def _fingerprint_extra(self):
        return f"{self._result_type.name};"

    def do_columnar(self, ctx, lv, rv, nv):
        from spark_rapids_tpu.ops import decimal_util as DU
        from spark_rapids_tpu.ops.base import _d
        from spark_rapids_tpu.ops.values import ColV

        xp = ctx.xp
        hi = DU._i64(xp, _d(lv))
        lo = DU._i64(xp, _d(rv))
        n = DU._i64(xp, _d(nv))
        exact = n < np.int64(2 ** 31)
        total_hi = hi + (lo >> np.int64(32))
        rem = lo & np.int64(0xFFFFFFFF)
        fits = (total_hi >= np.int64(-(2 ** 31))) & \
               (total_hi < np.int64(2 ** 31))
        val = xp.where(fits, total_hi, 0) * np.int64(2 ** 32) + rem
        val, ok2 = DU.fit_precision(xp, val, self._result_type.precision)
        ok = exact & fits & ok2
        return ColV(self._result_type, xp.where(ok, val, 0), ok)


class Sum(AggregateFunction):
    @property
    def data_type(self):
        return _sum_type(self.child.data_type)

    @property
    def _is_decimal(self):
        return getattr(self.child.data_type, "is_decimal", False)

    @property
    def _narrow_dec(self):
        return self._is_decimal and _narrow_decimal(self.child.data_type)

    def buffer_attrs(self):
        if self._narrow_dec:
            return [AttributeReference("sum_u", DataType.INT64, True),
                    AttributeReference("sum_n", DataType.INT64, False)]
        if self._is_decimal:
            return [AttributeReference("sum_hi", DataType.INT64, True),
                    AttributeReference("sum_lo", DataType.INT64, True),
                    AttributeReference("sum_n", DataType.INT64, False)]
        return [AttributeReference("sum", self.data_type, True)]

    def update_aggs(self):
        from spark_rapids_tpu.ops.cast import Cast

        if self._narrow_dec:
            return [("sum_u", "sum", _UnscaledRaw(self.child)),
                    ("sum_n", "count", self.child)]
        if self._is_decimal:
            return [("sum_hi", "sum", _UnscaledHi(self.child)),
                    ("sum_lo", "sum", _UnscaledLo(self.child)),
                    ("sum_n", "count", self.child)]
        src = self.child
        if src.data_type != self.data_type:
            src = Cast(src, self.data_type)
        return [("sum", "sum", src)]

    def merge_aggs(self):
        if self._narrow_dec:
            return [("sum_u", "sum"), ("sum_n", "sum")]
        if self._is_decimal:
            return [("sum_hi", "sum"), ("sum_lo", "sum"), ("sum_n", "sum")]
        return [("sum", "sum")]

    def evaluate_expression(self, buffers):
        if self._narrow_dec:
            return _NarrowDecimalSumFinish(buffers[0], buffers[1],
                                           self.data_type)
        if self._is_decimal:
            return _DecimalSumFinish(buffers[0], buffers[1], buffers[2],
                                     self.data_type)
        return buffers[0]

    def initial_buffer_values(self):
        if self._narrow_dec:
            return [None, 0]
        if self._is_decimal:
            # sum_n is declared non-nullable: the empty reduction must seed
            # it with 0, not SQL NULL (result NULL-ness comes from sum_hi/lo)
            return [None, None, 0]
        return [None]


class Count(AggregateFunction):
    """count(expr) — counts non-null; count(*) is Count(Literal(1))."""

    @property
    def data_type(self):
        return DataType.INT64

    @property
    def nullable(self):
        return False

    def buffer_attrs(self):
        return [AttributeReference("count", DataType.INT64, False)]

    def update_aggs(self):
        return [("count", "count", self.child)]

    def merge_aggs(self):
        return [("count", "sum")]

    def evaluate_expression(self, buffers):
        return buffers[0]

    def default_value(self):
        return 0

    def initial_buffer_values(self):
        return [0]


class _DecimalAvgFinish(BinaryExpression):
    """sum(decimal) / count, HALF_UP at Spark's avg scale (s + 4, bounded).
    Overflow of sum * 10^(rs - s) beyond int64 -> SQL NULL."""

    def __init__(self, sum_expr, count_expr, sum_scale, result_type):
        super().__init__(sum_expr, count_expr)
        self._sum_scale = sum_scale
        self._result_type = result_type

    def with_children(self, new_children):
        return _DecimalAvgFinish(new_children[0], new_children[1],
                                 self._sum_scale, self._result_type)

    @property
    def data_type(self):
        return self._result_type

    @property
    def nullable(self):
        return True

    def _fingerprint_extra(self):
        return f"{self._sum_scale}->{self._result_type.name};"

    def do_columnar(self, ctx, lv, rv):
        from spark_rapids_tpu.ops import decimal_util as DU
        from spark_rapids_tpu.ops.base import _d
        from spark_rapids_tpu.ops.values import ColV

        xp = ctx.xp
        k = self._result_type.scale - self._sum_scale
        num, ok1 = DU.checked_mul_pow10(xp, DU._i64(xp, _d(lv)), max(k, 0))
        q, ok2 = DU.div_half_up(xp, num, DU._i64(xp, _d(rv)))
        if k < 0:
            q, _ = DU.rescale(xp, q, self._sum_scale, self._result_type.scale)
        q, ok3 = DU.fit_precision(xp, q, self._result_type.precision)
        ok = ok1 & ok2 & ok3
        return ColV(self._result_type, xp.where(ok, q, 0), ok)


class Average(AggregateFunction):
    @property
    def _dec(self):
        dt = self.child.data_type
        return dt if getattr(dt, "is_decimal", False) else None

    @property
    def data_type(self):
        if self._dec is not None:
            from spark_rapids_tpu.ops import decimal_util as DU

            # Spark: avg(decimal(p,s)) -> decimal(p+4, s+4), bounded
            return DU.bounded(self._dec.precision + 4, self._dec.scale + 4)
        return DataType.FLOAT64

    @property
    def _narrow_dec(self):
        return self._dec is not None and _narrow_decimal(self._dec)

    def buffer_attrs(self):
        if self._narrow_dec:
            return [AttributeReference("sum_u", DataType.INT64, True),
                    AttributeReference("count", DataType.INT64, False)]
        if self._dec is not None:
            return [AttributeReference("sum_hi", DataType.INT64, True),
                    AttributeReference("sum_lo", DataType.INT64, True),
                    AttributeReference("count", DataType.INT64, False)]
        return [
            AttributeReference("sum", DataType.FLOAT64, True),
            AttributeReference("count", DataType.INT64, False),
        ]

    def update_aggs(self):
        from spark_rapids_tpu.ops.cast import Cast

        if self._narrow_dec:
            return [("sum_u", "sum", _UnscaledRaw(self.child)),
                    ("count", "count", self.child)]
        if self._dec is not None:
            return [("sum_hi", "sum", _UnscaledHi(self.child)),
                    ("sum_lo", "sum", _UnscaledLo(self.child)),
                    ("count", "count", self.child)]
        src = self.child
        if src.data_type is not DataType.FLOAT64:
            src = Cast(src, DataType.FLOAT64)
        return [("sum", "sum", src), ("count", "count", self.child)]

    def merge_aggs(self):
        if self._narrow_dec:
            return [("sum_u", "sum"), ("count", "sum")]
        if self._dec is not None:
            return [("sum_hi", "sum"), ("sum_lo", "sum"), ("count", "sum")]
        return [("sum", "sum"), ("count", "sum")]

    def evaluate_expression(self, buffers):
        from spark_rapids_tpu.ops.arithmetic import Divide
        from spark_rapids_tpu.ops.cast import Cast

        if self._narrow_dec:
            sum_type = _sum_type(self._dec)
            return _DecimalAvgFinish(
                _NarrowDecimalSumFinish(buffers[0], buffers[1], sum_type),
                buffers[1], sum_type.scale, self.data_type)
        if self._dec is not None:
            sum_type = _sum_type(self._dec)
            return _DecimalAvgFinish(
                _DecimalSumFinish(buffers[0], buffers[1], buffers[2],
                                  sum_type),
                buffers[2], sum_type.scale, self.data_type)
        return Divide(buffers[0], Cast(buffers[1], DataType.FLOAT64))

    def initial_buffer_values(self):
        if self._narrow_dec:
            return [None, 0]
        if self._dec is not None:
            return [None, None, 0]
        return [None, 0]


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, new_children):
        return First(new_children[0], self.ignore_nulls)

    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("first", self.data_type, True)]

    def update_aggs(self):
        op = "first_ignore_nulls" if self.ignore_nulls else "first"
        return [("first", op, self.child)]

    def merge_aggs(self):
        op = "first_ignore_nulls" if self.ignore_nulls else "first"
        return [("first", op)]

    def evaluate_expression(self, buffers):
        return buffers[0]

    def _fingerprint_extra(self):
        return f"{self.ignore_nulls};"


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, new_children):
        return Last(new_children[0], self.ignore_nulls)

    @property
    def data_type(self):
        return self.child.data_type

    def buffer_attrs(self):
        return [AttributeReference("last", self.data_type, True)]

    def update_aggs(self):
        op = "last_ignore_nulls" if self.ignore_nulls else "last"
        return [("last", op, self.child)]

    def merge_aggs(self):
        op = "last_ignore_nulls" if self.ignore_nulls else "last"
        return [("last", op)]

    def evaluate_expression(self, buffers):
        return buffers[0]

    def _fingerprint_extra(self):
        return f"{self.ignore_nulls};"


class Percentile(AggregateFunction):
    """Exact percentile(col, p): linear interpolation at rank p*(n-1) over
    the group's sorted non-null values, as DOUBLE (Spark's exact
    `percentile`; reference benchmark AggregatesWithPercentiles,
    mortgage/MortgageSpark.scala:367-390).

    HOLISTIC: not decomposable into update/merge partials (Spark runs it
    via ObjectHashAggregate for the same reason), so the planner skips the
    partial stage — raw rows exchange on the grouping keys and ONE
    complete-mode aggregation runs per partition over a single coalesced
    batch. On device the kernel is one (gid, value) sort + two boundary
    gathers + an interpolation (exec/rowkeys.segment_reduce "pct:<p>"),
    the TPU shape of cudf's group quantiles."""

    holistic = True

    def __init__(self, child: Expression, p: float):
        super().__init__(child)
        if not (0.0 <= float(p) <= 1.0):
            raise ValueError(f"percentile fraction must be in [0, 1]: {p}")
        self.p = float(p)

    def with_children(self, new_children):
        return Percentile(new_children[0], self.p)

    def _fingerprint_extra(self):
        return f"p={self.p!r};"

    @property
    def data_type(self):
        return DataType.FLOAT64

    def buffer_attrs(self):
        return [AttributeReference("pct", DataType.FLOAT64, True)]

    def update_aggs(self):
        from spark_rapids_tpu.ops.cast import Cast

        child = self.child
        if child.data_type is not DataType.FLOAT64:
            child = Cast(child, DataType.FLOAT64)
        return [("pct", f"pct:{self.p!r}", child)]

    def merge_aggs(self):
        # never reached: holistic plans have no partial stage. A loud op
        # name keeps a future planner regression from silently merging.
        return [("pct", "unmergeable")]

    def evaluate_expression(self, buffers):
        return buffers[0]
