"""Arithmetic expressions (reference:
org/apache/spark/sql/rapids/arithmetic.scala — +,-,*,/,div,pmod,remainder,
abs,signum,unary +/-; 227 LoC)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import (
    DataType,
    DecimalType,
    common_type,
    is_decimal,
)
from spark_rapids_tpu.ops import decimal_util as DU
from spark_rapids_tpu.ops.base import (
    BinaryExpression,
    UnaryExpression,
    _d,
    val_interval,
)
from spark_rapids_tpu.ops.values import ColV

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


class BinaryArithmetic(BinaryExpression):
    # per-op decimal precision rule (None -> decimal operands unsupported)
    _decimal_result = None

    def _decimal_types(self):
        """(left, right, result) DecimalTypes when this op runs in decimal
        space (at least one decimal operand, the other decimal-coercible)."""
        lt, rt = self.left.data_type, self.right.data_type
        if not (is_decimal(lt) or is_decimal(rt)):
            return None
        ld, rd = DU.as_decimal_type(lt), DU.as_decimal_type(rt)
        if ld is None or rd is None:
            return None  # decimal op float resolves via common_type -> double
        if type(self)._decimal_result is None:
            raise TypeError(
                f"{type(self).__name__} does not support decimal operands")
        return ld, rd, type(self)._decimal_result(ld, rd)

    @property
    def data_type(self):
        dts = self._decimal_types()
        if dts is not None:
            return dts[2]
        ct = common_type(self.left.data_type, self.right.data_type)
        if ct is None:
            raise TypeError(
                f"{type(self).__name__}: incompatible types "
                f"{self.left.data_type} / {self.right.data_type}"
            )
        return ct

    @property
    def nullable(self):
        # decimal arithmetic can overflow to NULL (Spark non-ANSI semantics)
        if self._decimal_types() is not None:
            return True
        return super().nullable

    # -- static interval rules (int32-narrowing proof; see columnar.batch) ---
    def _math_interval(self, li, ri):
        """Exact mathematical result interval from operand intervals (python
        ints, no wrap), or None. Per-op; conservative default."""
        return None

    def result_vrange(self, lv, rv):
        if not self.data_type.is_integral or self._decimal_types() is not None:
            return None
        iv = self._math_interval(val_interval(lv), val_interval(rv))
        if iv is None:
            return None
        # only claim a bound when no wrap can have occurred at the result type
        info = np.iinfo(self.data_type.to_np())
        if iv[0] >= int(info.min) and iv[1] <= int(info.max):
            return iv
        return None

    def _narrow_npdt(self, ctx, lv, rv):
        """np.int32 when int32 compute is provably exact for this op's
        int64 result (math interval and both operand values fit int32),
        else None. Remainder's pure mod chain is ring-exact whenever its
        FINAL value fits int32 (its _math_interval bounds that); Pmod's
        sign fix-up DIVIDES after an add that can wrap, so its kernel
        widens that one step to int64 (see Pmod.do_columnar)."""
        from spark_rapids_tpu.columnar.batch import (
            fits_int32,
            int64_narrowing_enabled,
        )

        if (not ctx.is_device or not getattr(ctx, "narrow", True)
                or not int64_narrowing_enabled()
                or self.data_type is not DataType.INT64):
            return None
        li, ri = val_interval(lv), val_interval(rv)
        if not (fits_int32(li) and fits_int32(ri)):
            return None
        mi = self._math_interval(li, ri)
        if fits_int32(mi):
            return np.dtype(np.int32)
        return None

    def _cast_operands(self, ctx, lv, rv):
        npdt = self._narrow_npdt(ctx, lv, rv) or self.data_type.to_np()
        types = (self.left.data_type, self.right.data_type)

        def cast(x, dt):
            # decimal operand entering a float op: unscale to its real value
            if is_decimal(dt) and npdt.kind == "f":
                x = x / float(DU.POW10[dt.scale]) if hasattr(x, "astype") \
                    else float(x) / float(DU.POW10[dt.scale])
            if hasattr(x, "astype"):
                return x.astype(npdt) if x.dtype != npdt else x
            return npdt.type(x)

        return cast(_d(lv), types[0]), cast(_d(rv), types[1])

    # -- shared decimal mod driver -------------------------------------------
    def _decimal_mod(self, ctx, lv, rv, positive: bool):
        """Truncated (or positive, for pmod) modulus at the common scale.
        Result scale is max(s1, s2), which the remainder precision rule
        always preserves (p <= 18 by construction, so no adjust)."""
        xp = ctx.xp
        ld, rd, res = self._decimal_types()
        s = max(ld.scale, rd.scale)
        l, ok1 = DU.rescale(xp, DU._i64(xp, _d(lv)), ld.scale, s)
        r, ok2 = DU.rescale(xp, DU._i64(xp, _d(rv)), rd.scale, s)
        safe_r = xp.where(r == 0, 1, r)

        def trunc_mod(a, n):
            q = a // n
            rem = a - q * n
            adj = (rem != 0) & ((a < 0) ^ (n < 0))
            return a - (q + adj.astype(np.int64)) * n

        m = trunc_mod(l, safe_r)
        if positive:
            m = xp.where(m < 0, trunc_mod(m + safe_r, safe_r), m)
        ok = ok1 & ok2  # r == 0 -> null is applied by eval_kernel
        return ColV(res, xp.where(ok, m, 0), ok)

    # -- shared decimal addsub/mul driver ------------------------------------
    def _decimal_addsub(self, ctx, lv, rv, sign: int):
        """Add/sub at the max operand scale, then round once to the result
        scale.  When precision adjustment shrinks the result scale below
        max(s1, s2), rescaling each operand independently before adding
        would round twice and can differ from Spark's exact-add-then-round
        by one ulp.  The upscaled operands are only bounded by int64, so the
        add carries an explicit wrap check; a wrapped intermediate is the
        documented intermediate-overflow NULL, never a wrong value."""
        xp = ctx.xp
        ld, rd, res = self._decimal_types()
        s = max(ld.scale, rd.scale)
        l, ok1 = DU.rescale(xp, DU._i64(xp, _d(lv)), ld.scale, s)
        r, ok2 = DU.rescale(xp, DU._i64(xp, _d(rv)), rd.scale, s)
        r = r if sign > 0 else -r
        out = l + r
        # the upscaled operands can each reach ~9.2e18, so the add itself can
        # wrap int64: same-sign inputs whose sum flips sign -> overflow NULL
        no_wrap = ~(((l >= 0) == (r >= 0)) & ((out >= 0) != (l >= 0)))
        ok = ok1 & ok2 & no_wrap
        if s != res.scale:
            out, ok4 = DU.rescale(xp, out, s, res.scale)
            ok = ok & ok4
        out, ok3 = DU.fit_precision(xp, out, res.precision)
        ok = ok & ok3
        return ColV(res, xp.where(ok, out, 0), ok)


class Add(BinaryArithmetic):
    _decimal_result = staticmethod(DU.add_result_type)

    def _math_interval(self, li, ri):
        if li is None or ri is None:
            return None
        return (li[0] + ri[0], li[1] + ri[1])

    def do_columnar(self, ctx, lv, rv):
        if self._decimal_types() is not None:
            return self._decimal_addsub(ctx, lv, rv, +1)
        l, r = self._cast_operands(ctx, lv, rv)
        return l + r


class Subtract(BinaryArithmetic):
    _decimal_result = staticmethod(DU.add_result_type)

    def _math_interval(self, li, ri):
        if li is None or ri is None:
            return None
        return (li[0] - ri[1], li[1] - ri[0])

    def do_columnar(self, ctx, lv, rv):
        if self._decimal_types() is not None:
            return self._decimal_addsub(ctx, lv, rv, -1)
        l, r = self._cast_operands(ctx, lv, rv)
        return l - r


class Multiply(BinaryArithmetic):
    _decimal_result = staticmethod(DU.multiply_result_type)

    def _math_interval(self, li, ri):
        if li is None or ri is None:
            return None
        corners = [a * b for a in li for b in ri]
        return (min(corners), max(corners))

    def do_columnar(self, ctx, lv, rv):
        dts = self._decimal_types()
        if dts is not None:
            xp = ctx.xp
            ld, rd, res = dts
            prod, ok1 = DU.checked_mul(xp, _d(lv), _d(rv))
            # natural scale is ld.scale + rd.scale; adjust may have shrunk it
            prod, ok2 = DU.rescale(xp, prod, ld.scale + rd.scale, res.scale)
            prod, ok3 = DU.fit_precision(xp, prod, res.precision)
            ok = ok1 & ok2 & ok3
            return ColV(res, xp.where(ok, prod, 0), ok)
        l, r = self._cast_operands(ctx, lv, rv)
        return l * r


class Divide(BinaryArithmetic):
    """SQL / — floating (Spark Divide), or decimal division with Spark's
    DecimalPrecision result type when both operands are decimal-coercible and
    at least one is decimal. x/0 -> null on both paths."""

    _decimal_result = staticmethod(DU.divide_result_type)

    @property
    def data_type(self):
        dts = self._decimal_types()
        if dts is not None:
            return dts[2]
        return DataType.FLOAT64

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            # division by zero yields SQL NULL
            xp = ctx.xp
            r = _d(rv)
            zero_div = (r == 0) if not isinstance(rv, ColV) else (rv.data == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            zero = np.zeros((), dtype=out.data.dtype)
            data = xp.where(validity, out.data, zero)
            return ColV(out.dtype, data, validity)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        dts = self._decimal_types()
        if dts is not None:
            ld, rd, res = dts
            l = DU._i64(xp, _d(lv))
            r = DU._i64(xp, _d(rv))
            # bring the numerator to result scale: num = l * 10^k with
            # k = res.scale - ld.scale + rd.scale, then HALF_UP divide
            k = res.scale - ld.scale + rd.scale
            if k >= 0:
                num, ok1 = DU.checked_mul_pow10(xp, l, k)
                q, ok2 = DU.div_half_up(xp, num, r)
            else:
                # extreme-scale corner: divide first, then scale down
                q0, ok1 = DU.div_half_up(xp, l, r)
                q, ok2 = DU.rescale(xp, q0, ld.scale - rd.scale, res.scale)
            q, ok3 = DU.fit_precision(xp, q, res.precision)
            ok = ok1 & ok2 & ok3
            return ColV(res, xp.where(ok, q, 0), ok)
        npdt = self.data_type.to_np()
        l, r = _d(lv), _d(rv)
        l = l.astype(npdt) if hasattr(l, "astype") else float(l)
        r_arr = r.astype(npdt) if hasattr(r, "astype") else float(r)
        safe_r = xp.where(r_arr == 0, 1.0, r_arr) if hasattr(r_arr, "dtype") else \
            (1.0 if r_arr == 0 else r_arr)
        return l / safe_r


def _scalar_zero(v):
    from spark_rapids_tpu.ops.values import ScalarV

    return isinstance(v, ScalarV) and v.value == 0


class IntegralDivide(BinaryExpression):
    """SQL div — integer division returning LONG (Spark IntegralDivide)."""

    @property
    def data_type(self):
        return DataType.INT64

    def result_vrange(self, lv, rv):
        # |a div n| <= |a| except the INT64_MIN/-1 wrap corner; the result
        # sign follows sign(a)*sign(n), so without a known divisor sign the
        # bound must be symmetric (10 div -3 = -3)
        li, ri = val_interval(lv), val_interval(rv)
        if li is None or li[0] <= _I64_MIN:
            return None
        m = max(abs(li[0]), abs(li[1]))
        if li[0] >= 0 and ri is not None and ri[0] >= 0:
            return (0, m)
        return (-m, m)

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            xp = ctx.xp
            zero_div = (rv.data == 0) if isinstance(rv, ColV) else (_d(rv) == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            return ColV(out.dtype, xp.where(validity, out.data, 0), validity,
                        vrange=out.vrange)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        l = _d(lv)
        l = l.astype(np.int64) if hasattr(l, "astype") else np.int64(l)
        r = _d(rv)
        r = r.astype(np.int64) if hasattr(r, "astype") else int(r)
        lt = DU.as_decimal_type(self.left.data_type) \
            if is_decimal(self.left.data_type) else None
        rt = DU.as_decimal_type(self.right.data_type) \
            if is_decimal(self.right.data_type) else None
        if lt is not None or rt is not None:
            # a div b over decimals = trunc(a/b) on the *logical* values:
            # scale the numerator (or denominator) so both sides share one
            # scale; overflow -> NULL
            s1 = lt.scale if lt is not None else 0
            s2 = rt.scale if rt is not None else 0
            l = DU._i64(xp, l)
            r = DU._i64(xp, r)
            ok = xp.ones_like(l, dtype=bool)
            if s2 > s1:
                l, ok = DU.checked_mul_pow10(xp, l, s2 - s1)
            elif s1 > s2:
                r, ok = DU.checked_mul_pow10(xp, r, s1 - s2)
            safe_r = xp.where(r == 0, 1, r)
            q = l // safe_r
            rem = l - q * safe_r
            adj = (rem != 0) & ((l < 0) ^ (safe_r < 0))
            q = q + adj.astype(np.int64)
            return ColV(DataType.INT64, xp.where(ok, q, 0), ok)
        safe_r = xp.where(r == 0, 1, r) if hasattr(r, "dtype") else (1 if r == 0 else r)
        # SQL div truncates toward zero; // floors — fix up
        q = l // safe_r
        rem = l - q * safe_r
        adj = (rem != 0) & ((l < 0) ^ (safe_r < 0))
        return q + adj.astype(np.int64)


class Remainder(BinaryArithmetic):
    """SQL % — sign follows the dividend (C semantics, like Spark)."""

    _decimal_result = staticmethod(DU.remainder_result_type)

    def _math_interval(self, li, ri):
        # |a % n| <= min(|a|, |n| - 1); sign follows the dividend. The
        # wrapped int32 chain is ring-exact because this final bound always
        # fits (divisor-zero lanes become NULL, value irrelevant).
        if li is None or ri is None:
            return None
        mn = max(abs(ri[0]), abs(ri[1]))
        m = min(max(abs(li[0]), abs(li[1])), max(mn - 1, 0))
        return (0 if li[0] >= 0 else -m, 0 if li[1] <= 0 else m)

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            xp = ctx.xp
            zero_div = (rv.data == 0) if isinstance(rv, ColV) else (_d(rv) == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            return ColV(out.dtype, xp.where(validity, out.data, 0), validity,
                        vrange=out.vrange)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        if self._decimal_types() is not None:
            return self._decimal_mod(ctx, lv, rv, positive=False)
        xp = ctx.xp
        npdt = self._narrow_npdt(ctx, lv, rv) or self.data_type.to_np()
        l, r = _d(lv), _d(rv)
        l = l.astype(npdt) if hasattr(l, "astype") else l
        r = r.astype(npdt) if hasattr(r, "astype") else r
        safe_r = xp.where(r == 0, 1, r) if hasattr(r, "dtype") else (1 if r == 0 else r)
        if npdt.kind == "f":
            return xp.fmod(l, safe_r)
        # truncated (toward-zero) remainder for ints: l - trunc_div(l,r)*r
        q = l // safe_r
        rem = l - q * safe_r
        adj = (rem != 0) & ((l < 0) ^ (safe_r < 0))
        return l - (q + adj) * safe_r


class Pmod(BinaryArithmetic):
    """pmod(a, b): positive modulus (reference: GpuPmod)."""

    _decimal_result = staticmethod(DU.remainder_result_type)

    def _math_interval(self, li, ri):
        # pmod's sign follows the DIVISOR (Spark/Hive): pmod(-5, 3) = 1 but
        # pmod(-5, -3) = -2. |result| <= |divisor| - 1 always; a
        # non-negative dividend with a non-negative divisor also bounds by
        # the dividend. (divisor-zero lanes become NULL, value irrelevant)
        if li is None or ri is None:
            return None
        m = max(max(abs(ri[0]), abs(ri[1])) - 1, 0)
        if li[0] >= 0 and ri[0] >= 0:
            return (0, min(m, max(abs(li[0]), abs(li[1]))))
        lo = 0 if ri[0] >= 0 else -m
        hi = 0 if ri[1] <= 0 else m
        return (lo, hi)

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            xp = ctx.xp
            zero_div = (rv.data == 0) if isinstance(rv, ColV) else (_d(rv) == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            return ColV(out.dtype, xp.where(validity, out.data, 0), validity,
                        vrange=out.vrange)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        if self._decimal_types() is not None:
            return self._decimal_mod(ctx, lv, rv, positive=True)
        xp = ctx.xp
        npdt = self._narrow_npdt(ctx, lv, rv) or self.data_type.to_np()
        l, r = _d(lv), _d(rv)
        l = l.astype(npdt) if hasattr(l, "astype") else l
        r = r.astype(npdt) if hasattr(r, "astype") else r
        safe_r = xp.where(r == 0, 1, r) if hasattr(r, "dtype") else (1 if r == 0 else r)
        if npdt.kind == "f":
            m = xp.fmod(l, safe_r)
            return xp.where(m < 0, xp.fmod(m + safe_r, safe_r), m)

        # java semantics: r = truncated a % n; if r < 0 then trunc_mod(r+n, n)
        def trunc_mod(a, n):
            q = a // n
            rem = a - q * n
            adj = (rem != 0) & ((a < 0) ^ (n < 0))
            return a - (q + adj) * n

        m = trunc_mod(l, safe_r)
        if np.dtype(npdt).itemsize < 8 and hasattr(m, "astype"):
            # the sign fix-up intermediate m + r spans up to 2|r| - 1, which
            # overflows int32 when |r| > 2^30 — and the trunc_mod that
            # follows DIVIDES, so the wrap is not ring-exact (unlike
            # Remainder's pure mod chain). Widen just the fix-up; the final
            # pmod value always fits the narrow lane (|v| <= |r| - 1).
            mw = m.astype(np.int64)
            rw = safe_r.astype(np.int64) if hasattr(safe_r, "astype") \
                else np.int64(safe_r)
            fix = trunc_mod(mw + rw, rw).astype(npdt)
            return xp.where(m < 0, fix, m)
        return xp.where(m < 0, trunc_mod(m + safe_r, safe_r), m)


class UnaryMinus(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def result_vrange(self, v):
        iv = val_interval(v)
        if iv is None or not self.data_type.is_integral:
            return None
        info = np.iinfo(self.data_type.to_np())
        # claim only when no wrap at the RESULT type (e.g. INT negate of
        # INT32_MIN wraps and the math interval would be a lie)
        if -iv[1] >= int(info.min) and -iv[0] <= int(info.max):
            return (-iv[1], -iv[0])
        return None

    def do_columnar(self, ctx, v):
        data = v.data
        iv = val_interval(v)
        # only a logically-INT64 column narrowed to int32 lanes may widen:
        # -INT32_MIN wraps in the narrowed lane but not in int64. A plain
        # SQL INT keeps Java wrap semantics (-INT32_MIN == INT32_MIN).
        if (self.data_type is DataType.INT64
                and hasattr(data, "astype") and data.dtype == np.int32
                and (iv is None or -iv[0] > (1 << 31) - 1)):
            data = data.astype(np.int64)
        return -data


class UnaryPositive(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def result_vrange(self, v):
        return val_interval(v)

    def do_columnar(self, ctx, v):
        return v.data


class Abs(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def result_vrange(self, v):
        iv = val_interval(v)
        if iv is None or not self.data_type.is_integral:
            return None
        info = np.iinfo(self.data_type.to_np())
        hi = max(abs(iv[0]), abs(iv[1]))
        if hi > int(info.max):  # abs(MIN) wraps at the result type
            return None
        lo = 0 if iv[0] <= 0 <= iv[1] else min(abs(iv[0]), abs(iv[1]))
        return (lo, hi)

    def do_columnar(self, ctx, v):
        data = v.data
        iv = val_interval(v)
        # see UnaryMinus: widen only int32-narrowed LONG lanes; SQL INT
        # keeps Java wrap semantics (abs(INT32_MIN) == INT32_MIN)
        if (self.data_type is DataType.INT64
                and hasattr(data, "astype") and data.dtype == np.int32
                and (iv is None or -iv[0] > (1 << 31) - 1)):
            data = data.astype(np.int64)
        return ctx.xp.abs(data)


class Signum(UnaryExpression):
    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, v):
        return ctx.xp.sign(v.data).astype(self.data_type.to_np() if not ctx.is_device
                                          else _phys(ctx))


def _phys(ctx):
    from spark_rapids_tpu.columnar.batch import physical_np_dtype

    return physical_np_dtype(DataType.FLOAT64)
