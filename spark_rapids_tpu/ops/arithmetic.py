"""Arithmetic expressions (reference:
org/apache/spark/sql/rapids/arithmetic.scala — +,-,*,/,div,pmod,remainder,
abs,signum,unary +/-; 227 LoC)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType, common_type
from spark_rapids_tpu.ops.base import BinaryExpression, UnaryExpression, _d
from spark_rapids_tpu.ops.values import ColV


class BinaryArithmetic(BinaryExpression):
    @property
    def data_type(self):
        ct = common_type(self.left.data_type, self.right.data_type)
        if ct is None:
            raise TypeError(
                f"{type(self).__name__}: incompatible types "
                f"{self.left.data_type} / {self.right.data_type}"
            )
        return ct

    def _cast_operands(self, ctx, lv, rv):
        npdt = self.data_type.to_np()

        def cast(x):
            if hasattr(x, "astype"):
                return x.astype(npdt) if x.dtype != npdt else x
            return npdt.type(x)

        return cast(_d(lv)), cast(_d(rv))


class Add(BinaryArithmetic):
    def do_columnar(self, ctx, lv, rv):
        l, r = self._cast_operands(ctx, lv, rv)
        return l + r


class Subtract(BinaryArithmetic):
    def do_columnar(self, ctx, lv, rv):
        l, r = self._cast_operands(ctx, lv, rv)
        return l - r


class Multiply(BinaryArithmetic):
    def do_columnar(self, ctx, lv, rv):
        l, r = self._cast_operands(ctx, lv, rv)
        return l * r


class Divide(BinaryExpression):
    """SQL / — always floating (Spark Divide); x/0 -> null handled by the
    meta layer marking nullable and the kernel emitting NaN->null."""

    @property
    def data_type(self):
        return DataType.FLOAT64

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            # division by zero yields SQL NULL
            xp = ctx.xp
            r = _d(rv)
            zero_div = (r == 0) if not isinstance(rv, ColV) else (rv.data == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            data = xp.where(validity, out.data, 0.0)
            return ColV(out.dtype, data, validity)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        npdt = self.data_type.to_np()
        l, r = _d(lv), _d(rv)
        l = l.astype(npdt) if hasattr(l, "astype") else float(l)
        r_arr = r.astype(npdt) if hasattr(r, "astype") else float(r)
        safe_r = xp.where(r_arr == 0, 1.0, r_arr) if hasattr(r_arr, "dtype") else \
            (1.0 if r_arr == 0 else r_arr)
        return l / safe_r


def _scalar_zero(v):
    from spark_rapids_tpu.ops.values import ScalarV

    return isinstance(v, ScalarV) and v.value == 0


class IntegralDivide(BinaryExpression):
    """SQL div — integer division returning LONG (Spark IntegralDivide)."""

    @property
    def data_type(self):
        return DataType.INT64

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            xp = ctx.xp
            zero_div = (rv.data == 0) if isinstance(rv, ColV) else (_d(rv) == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            return ColV(out.dtype, xp.where(validity, out.data, 0), validity)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        l = _d(lv)
        l = l.astype(np.int64) if hasattr(l, "astype") else np.int64(l)
        r = _d(rv)
        r = r.astype(np.int64) if hasattr(r, "astype") else int(r)
        safe_r = xp.where(r == 0, 1, r) if hasattr(r, "dtype") else (1 if r == 0 else r)
        # SQL div truncates toward zero; // floors — fix up
        q = l // safe_r
        rem = l - q * safe_r
        adj = (rem != 0) & ((l < 0) ^ (safe_r < 0))
        return q + adj.astype(np.int64)


class Remainder(BinaryArithmetic):
    """SQL % — sign follows the dividend (C semantics, like Spark)."""

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            xp = ctx.xp
            zero_div = (rv.data == 0) if isinstance(rv, ColV) else (_d(rv) == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            return ColV(out.dtype, xp.where(validity, out.data, 0), validity)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        npdt = self.data_type.to_np()
        l, r = _d(lv), _d(rv)
        l = l.astype(npdt) if hasattr(l, "astype") else l
        r = r.astype(npdt) if hasattr(r, "astype") else r
        safe_r = xp.where(r == 0, 1, r) if hasattr(r, "dtype") else (1 if r == 0 else r)
        if npdt.kind == "f":
            return xp.fmod(l, safe_r)
        # truncated (toward-zero) remainder for ints: l - trunc_div(l,r)*r
        q = l // safe_r
        rem = l - q * safe_r
        adj = (rem != 0) & ((l < 0) ^ (safe_r < 0))
        return l - (q + adj) * safe_r


class Pmod(BinaryArithmetic):
    """pmod(a, b): positive modulus (reference: GpuPmod)."""

    @property
    def nullable(self):
        return True

    def eval_kernel(self, ctx, lv, rv):
        out = super().eval_kernel(ctx, lv, rv)
        if isinstance(out, ColV):
            xp = ctx.xp
            zero_div = (rv.data == 0) if isinstance(rv, ColV) else (_d(rv) == 0)
            validity = out.validity & ctx.xp.logical_not(zero_div)
            return ColV(out.dtype, xp.where(validity, out.data, 0), validity)
        if out.value is not None and _scalar_zero(rv):
            out.value = None
        return out

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        npdt = self.data_type.to_np()
        l, r = _d(lv), _d(rv)
        l = l.astype(npdt) if hasattr(l, "astype") else l
        r = r.astype(npdt) if hasattr(r, "astype") else r
        safe_r = xp.where(r == 0, 1, r) if hasattr(r, "dtype") else (1 if r == 0 else r)
        if npdt.kind == "f":
            m = xp.fmod(l, safe_r)
            return xp.where(m < 0, xp.fmod(m + safe_r, safe_r), m)

        # java semantics: r = truncated a % n; if r < 0 then trunc_mod(r+n, n)
        def trunc_mod(a, n):
            q = a // n
            rem = a - q * n
            adj = (rem != 0) & ((a < 0) ^ (n < 0))
            return a - (q + adj) * n

        m = trunc_mod(l, safe_r)
        return xp.where(m < 0, trunc_mod(m + safe_r, safe_r), m)


class UnaryMinus(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def do_columnar(self, ctx, v):
        return -v.data


class UnaryPositive(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def do_columnar(self, ctx, v):
        return v.data


class Abs(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def do_columnar(self, ctx, v):
        return ctx.xp.abs(v.data)


class Signum(UnaryExpression):
    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, v):
        return ctx.xp.sign(v.data).astype(self.data_type.to_np() if not ctx.is_device
                                          else _phys(ctx))


def _phys(ctx):
    from spark_rapids_tpu.columnar.batch import physical_np_dtype

    return physical_np_dtype(DataType.FLOAT64)
