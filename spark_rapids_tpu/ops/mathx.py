"""Math expressions (reference: mathExpressions.scala, 378 LoC —
trig/log/exp/sqrt/cbrt/rint/pow etc.)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import BinaryExpression, UnaryExpression, _d


class UnaryMath(UnaryExpression):
    """double -> double math fn."""

    _fn = None  # name of the xp function

    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, v):
        xp = ctx.xp
        data = v.data
        if data.dtype.kind != "f":
            data = data.astype(np.float64 if not ctx.is_device else _f(ctx))
        return getattr(xp, self._fn)(data)


def _f(ctx):
    from spark_rapids_tpu.columnar.batch import physical_np_dtype

    return physical_np_dtype(DataType.FLOAT64)


class Sin(UnaryMath):
    _fn = "sin"


class Cos(UnaryMath):
    _fn = "cos"


class Tan(UnaryMath):
    _fn = "tan"


class Asin(UnaryMath):
    _fn = "arcsin"


class Acos(UnaryMath):
    _fn = "arccos"


class Atan(UnaryMath):
    _fn = "arctan"


class Sinh(UnaryMath):
    _fn = "sinh"


class Cosh(UnaryMath):
    _fn = "cosh"


class Tanh(UnaryMath):
    _fn = "tanh"


class Sqrt(UnaryMath):
    _fn = "sqrt"


class Exp(UnaryMath):
    _fn = "exp"


class Expm1(UnaryMath):
    _fn = "expm1"


class Log(UnaryMath):
    _fn = "log"


class Log1p(UnaryMath):
    _fn = "log1p"


class Log2(UnaryMath):
    _fn = "log2"


class Log10(UnaryMath):
    _fn = "log10"


class Cbrt(UnaryMath):
    _fn = "cbrt"


class Rint(UnaryMath):
    _fn = "rint"


class Floor(UnaryExpression):
    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        return ctx.xp.floor(v.data.astype(_f(ctx) if ctx.is_device else np.float64)) \
            .astype(np.int64)


class Ceil(UnaryExpression):
    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        return ctx.xp.ceil(v.data.astype(_f(ctx) if ctx.is_device else np.float64)) \
            .astype(np.int64)


class ToDegrees(UnaryMath):
    _fn = "degrees"


class ToRadians(UnaryMath):
    _fn = "radians"


class Pow(BinaryExpression):
    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        f = _f(ctx) if ctx.is_device else np.float64

        def cast(x):
            return x.astype(f) if hasattr(x, "astype") else float(x)

        return xp.power(cast(_d(lv)), cast(_d(rv)))


class Atan2(BinaryExpression):
    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        f = _f(ctx) if ctx.is_device else np.float64

        def cast(x):
            return x.astype(f) if hasattr(x, "astype") else float(x)

        return xp.arctan2(cast(_d(lv)), cast(_d(rv)))


class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize -0.0 -> 0.0 and every NaN to one canonical NaN
    (reference: NormalizeNaNAndZero, NormalizeFloatingNumbers.scala — Spark
    inserts it over float group/join keys). The engine's key machinery
    (exec/rowkeys key_proxy, ops/hashing float bits, the CPU oracle's
    _canonical_key) already normalizes during grouping/joining; this
    expression is the user-visible/value-level form."""

    @property
    def data_type(self):
        return self.child.data_type

    def do_columnar(self, ctx, v):
        xp = ctx.xp
        d = _d(v)
        dt = d.dtype if hasattr(d, "dtype") else np.float64
        d = xp.where(d == 0.0, xp.asarray(0.0, dtype=dt), d)
        return xp.where(xp.isnan(d), xp.asarray(float("nan"), dtype=dt), d)
