"""Math expressions (reference: mathExpressions.scala, 378 LoC —
trig/log/exp/sqrt/cbrt/rint/pow etc.)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import BinaryExpression, UnaryExpression, _d


class UnaryMath(UnaryExpression):
    """double -> double math fn."""

    _fn = None  # name of the xp function

    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, v):
        return getattr(ctx.xp, self._fn)(
            _to_float(ctx, v.data, ints_only=True))


def _f(ctx):
    from spark_rapids_tpu.columnar.batch import physical_np_dtype

    return physical_np_dtype(DataType.FLOAT64)


def _to_float(ctx, x, ints_only=False):
    """Coerce a value/array to the double-compute dtype of this context
    (f32 on TPU hardware, f64 on the CPU oracle) — the ONE place the
    device-float policy lives for math kernels. ints_only=True leaves
    float inputs at their stored width (unary-math pass-through)."""
    f = _f(ctx) if ctx.is_device else np.float64
    if hasattr(x, "astype"):
        if ints_only and x.dtype.kind == "f":
            return x
        return x.astype(f)
    return float(x)


class Sin(UnaryMath):
    _fn = "sin"


class Cos(UnaryMath):
    _fn = "cos"


class Tan(UnaryMath):
    _fn = "tan"


class Asin(UnaryMath):
    _fn = "arcsin"


class Acos(UnaryMath):
    _fn = "arccos"


class Atan(UnaryMath):
    _fn = "arctan"


class Sinh(UnaryMath):
    _fn = "sinh"


class Cosh(UnaryMath):
    _fn = "cosh"


class Tanh(UnaryMath):
    _fn = "tanh"


class Sqrt(UnaryMath):
    _fn = "sqrt"


class Exp(UnaryMath):
    _fn = "exp"


class Expm1(UnaryMath):
    _fn = "expm1"


class Log(UnaryMath):
    _fn = "log"


class Log1p(UnaryMath):
    _fn = "log1p"


class Log2(UnaryMath):
    _fn = "log2"


class Log10(UnaryMath):
    _fn = "log10"


class Cbrt(UnaryMath):
    _fn = "cbrt"


class Asinh(UnaryMath):
    _fn = "arcsinh"


class Acosh(UnaryMath):
    _fn = "arccosh"


class Atanh(UnaryMath):
    _fn = "arctanh"


class Cot(UnaryMath):
    """cot(x) = 1/tan(x) (reference: mathExpressions.scala GpuCot; Spark
    returns Infinity at x=0, which 1/tan delivers for free)."""

    def do_columnar(self, ctx, v):
        return 1.0 / ctx.xp.tan(_to_float(ctx, v.data, ints_only=True))


class Logarithm(BinaryExpression):
    """log(base, x) (reference: mathExpressions.scala GpuLogarithm —
    lowered as log(x)/log(base), matching Spark's StrictMath identity)."""

    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, lv, rv):
        xp = ctx.xp
        return xp.log(_to_float(ctx, _d(rv))) / xp.log(_to_float(ctx, _d(lv)))


class Rint(UnaryMath):
    _fn = "rint"


class Floor(UnaryExpression):
    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        return ctx.xp.floor(v.data.astype(_f(ctx) if ctx.is_device else np.float64)) \
            .astype(np.int64)


class Ceil(UnaryExpression):
    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        return ctx.xp.ceil(v.data.astype(_f(ctx) if ctx.is_device else np.float64)) \
            .astype(np.int64)


class ToDegrees(UnaryMath):
    _fn = "degrees"


class ToRadians(UnaryMath):
    _fn = "radians"


class Pow(BinaryExpression):
    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, lv, rv):
        return ctx.xp.power(_to_float(ctx, _d(lv)), _to_float(ctx, _d(rv)))


class Atan2(BinaryExpression):
    @property
    def data_type(self):
        return DataType.FLOAT64

    def do_columnar(self, ctx, lv, rv):
        return ctx.xp.arctan2(_to_float(ctx, _d(lv)),
                              _to_float(ctx, _d(rv)))


class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize -0.0 -> 0.0 and every NaN to one canonical NaN
    (reference: NormalizeNaNAndZero, NormalizeFloatingNumbers.scala — Spark
    inserts it over float group/join keys). The engine's key machinery
    (exec/rowkeys key_proxy, ops/hashing float bits, the CPU oracle's
    _canonical_key) already normalizes during grouping/joining; this
    expression is the user-visible/value-level form."""

    @property
    def data_type(self):
        return self.child.data_type

    def do_columnar(self, ctx, v):
        xp = ctx.xp
        d = _d(v)
        dt = d.dtype if hasattr(d, "dtype") else np.float64
        d = xp.where(d == 0.0, xp.asarray(0.0, dtype=dt), d)
        return xp.where(xp.isnan(d), xp.asarray(float("nan"), dtype=dt), d)
