"""Predicates and logical operators (reference: predicates.scala 621 LoC +
GpuInSet.scala). And/Or use Kleene three-valued logic like Spark."""

from __future__ import annotations

from typing import Sequence

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import (
    BinaryExpression,
    Expression,
    UnaryExpression,
    _d,
)
from spark_rapids_tpu.ops.values import ColV, ScalarV


class BinaryComparison(BinaryExpression):
    @property
    def data_type(self):
        return DataType.BOOL

    def _operands(self, ctx, lv, rv):
        # string comparisons never reach here — each subclass short-circuits
        # to the string kernels first
        lt, rt = self.left.data_type, self.right.data_type
        if getattr(lt, "is_decimal", False) or getattr(rt, "is_decimal", False):
            from spark_rapids_tpu.ops import decimal_util as DU

            ld, rd = DU.as_decimal_type(lt), DU.as_decimal_type(rt)
            if ld is not None and rd is not None:
                s = max(ld.scale, rd.scale)
                return (DU.compare_rescale(ctx.xp, _d(lv), ld.scale, s),
                        DU.compare_rescale(ctx.xp, _d(rv), rd.scale, s))
            # decimal vs float: compare in floating space
            def unscale(x, dt):
                d = DU.as_decimal_type(dt)
                if d is None:
                    return x
                return x / float(DU.POW10[d.scale]) if hasattr(x, "astype") \
                    else float(x) / float(DU.POW10[d.scale])

            return unscale(_d(lv), lt), unscale(_d(rv), rt)
        return _d(lv), _d(rv)


class EqualTo(BinaryComparison):
    def do_columnar(self, ctx, lv, rv):
        if self.left.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            return S.string_equal(ctx, lv, rv)
        l, r = self._operands(ctx, lv, rv)
        return l == r


class LessThan(BinaryComparison):
    def do_columnar(self, ctx, lv, rv):
        if self.left.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            return S.string_compare(ctx, lv, rv, "lt")
        l, r = self._operands(ctx, lv, rv)
        return l < r


class LessThanOrEqual(BinaryComparison):
    def do_columnar(self, ctx, lv, rv):
        if self.left.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            return S.string_compare(ctx, lv, rv, "le")
        l, r = self._operands(ctx, lv, rv)
        return l <= r


class GreaterThan(BinaryComparison):
    def do_columnar(self, ctx, lv, rv):
        if self.left.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            return S.string_compare(ctx, lv, rv, "gt")
        l, r = self._operands(ctx, lv, rv)
        return l > r


class GreaterThanOrEqual(BinaryComparison):
    def do_columnar(self, ctx, lv, rv):
        if self.left.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            return S.string_compare(ctx, lv, rv, "ge")
        l, r = self._operands(ctx, lv, rv)
        return l >= r


class EqualNullSafe(BinaryExpression):
    """<=> — null-safe equality: NULL<=>NULL is true, never returns null."""

    @property
    def data_type(self):
        return DataType.BOOL

    @property
    def nullable(self):
        return False

    def eval_kernel(self, ctx, lv, rv):
        xp = ctx.xp

        def as_col(v):
            if isinstance(v, ScalarV):
                from spark_rapids_tpu.ops.values import broadcast_scalar

                if v.dtype is DataType.STRING:
                    return v
                return broadcast_scalar(ctx, v)
            return v

        if self.left.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            eq = S.string_equal(ctx, lv, rv)
            lvalid = lv.validity if isinstance(lv, ColV) else \
                xp.full((ctx.capacity,), not lv.is_null)
            rvalid = rv.validity if isinstance(rv, ColV) else \
                xp.full((ctx.capacity,), not rv.is_null)
        else:
            lc, rc = as_col(lv), as_col(rv)
            eq = lc.data == rc.data
            lvalid, rvalid = lc.validity, rc.validity
        both_valid = lvalid & rvalid
        both_null = ~lvalid & ~rvalid
        data = (both_valid & eq) | both_null
        validity = xp.ones((ctx.capacity,), dtype=bool)
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = data & validity
        return ColV(DataType.BOOL, data, validity)


class And(BinaryExpression):
    """Kleene AND: F&null=F, T&null=null."""

    @property
    def data_type(self):
        return DataType.BOOL

    def eval_kernel(self, ctx, lv, rv):
        xp = ctx.xp
        ld, lval = _bool_parts(ctx, lv)
        rd, rval = _bool_parts(ctx, rv)
        data = ld & rd
        false_somewhere = (~ld & lval) | (~rd & rval)
        validity = (lval & rval) | false_somewhere
        data = data & validity
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = data & validity
        return ColV(DataType.BOOL, data, validity)


class Or(BinaryExpression):
    """Kleene OR: T|null=T, F|null=null."""

    @property
    def data_type(self):
        return DataType.BOOL

    def eval_kernel(self, ctx, lv, rv):
        xp = ctx.xp
        ld, lval = _bool_parts(ctx, lv)
        rd, rval = _bool_parts(ctx, rv)
        data = ld | rd
        true_somewhere = (ld & lval) | (rd & rval)
        validity = (lval & rval) | true_somewhere
        data = data & validity
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = data & validity
        return ColV(DataType.BOOL, data, validity)


def _bool_parts(ctx, v):
    xp = ctx.xp
    if isinstance(v, ScalarV):
        if v.is_null:
            return (xp.zeros((ctx.capacity,), dtype=bool),
                    xp.zeros((ctx.capacity,), dtype=bool))
        return (xp.full((ctx.capacity,), bool(v.value)),
                xp.ones((ctx.capacity,), dtype=bool))
    return v.data.astype(bool), v.validity


class Not(UnaryExpression):
    @property
    def data_type(self):
        return DataType.BOOL

    def do_columnar(self, ctx, v):
        return ~v.data.astype(bool)


class In(Expression):
    """value IN (list of foldable literals) (reference: GpuInSet)."""

    def __init__(self, value: Expression, candidates: Sequence[Expression]):
        self.value = value
        self.candidates = tuple(candidates)

    def children(self):
        return (self.value,) + self.candidates

    def with_children(self, new_children):
        return In(new_children[0], new_children[1:])

    @property
    def data_type(self):
        return DataType.BOOL

    def eval_kernel(self, ctx, v, *cand_vals):
        xp = ctx.xp
        if isinstance(v, ScalarV):
            if v.is_null:
                return ScalarV(DataType.BOOL, None)
            hit = any((not c.is_null) and c.value == v.value for c in cand_vals)
            has_null = any(c.is_null for c in cand_vals)
            return ScalarV(DataType.BOOL, True if hit else (None if has_null else False))
        acc = xp.zeros((ctx.capacity,), dtype=bool)
        has_null_candidate = False
        for c in cand_vals:
            if c.is_null:
                has_null_candidate = True
                continue
            if self.value.data_type is DataType.STRING:
                from spark_rapids_tpu.columnar import strings as S

                acc = acc | S.string_equal(ctx, v, c)
            else:
                acc = acc | (v.data == c.value)
        # SQL: x IN (...) with a NULL candidate -> NULL unless matched
        validity = v.validity & (acc | (not has_null_candidate))
        data = acc & validity
        return ColV(DataType.BOOL, data, validity)

    def _fingerprint_extra(self):
        return ""
