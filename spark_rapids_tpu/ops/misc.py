"""Nondeterministic / context expressions (reference: GpuRandomExpressions.scala,
GpuMonotonicallyIncreasingID, GpuSparkPartitionID, GpuInputFileBlock with
coalesce poisoning — GpuExpressions.scala:81-85)."""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import LeafExpression
from spark_rapids_tpu.ops.values import ColV


class Rand(LeafExpression):
    """rand(seed): uniform [0,1). Nondeterministic — per-partition stream
    seeded by (seed, partition); values differ from the CPU oracle by design
    (the reference marks rand INCOMPAT for the same reason)."""

    def __init__(self, seed: int = 0):
        self.seed = seed

    @property
    def data_type(self):
        return DataType.FLOAT64

    @property
    def nullable(self):
        return False

    @property
    def deterministic(self):
        return False

    def eval_kernel(self, ctx):
        if ctx.is_device:
            import jax

            key = jax.random.key(
                (self.seed * 1_000_003 + ctx.partition_id) & 0x7FFFFFFF
            )
            key = jax.random.fold_in(key, ctx.row_start)
            from spark_rapids_tpu.columnar.batch import physical_np_dtype

            data = jax.random.uniform(
                key, (ctx.capacity,),
                dtype=physical_np_dtype(DataType.FLOAT64))
            validity = ctx.row_mask()
            return ColV(DataType.FLOAT64, data * validity, validity)
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + ctx.partition_id) % (2**31))
        rng.randint(0, 2**31)  # advance so row_start matters
        data = rng.uniform(size=ctx.capacity)
        return ColV(DataType.FLOAT64, data,
                    np.ones((ctx.capacity,), dtype=bool))

    def _fingerprint_extra(self):
        return f"{self.seed};"


class MonotonicallyIncreasingID(LeafExpression):
    """partition_id << 33 | row_index (Spark's exact layout)."""

    @property
    def data_type(self):
        return DataType.INT64

    @property
    def nullable(self):
        return False

    @property
    def deterministic(self):
        return False

    def eval_kernel(self, ctx):
        xp = ctx.xp
        # partition_id/row_start may be traced scalars on the device path
        base = xp.asarray(ctx.partition_id, dtype=np.int64) * np.int64(1 << 33)
        ids = base + ctx.row_start + xp.arange(ctx.capacity, dtype=np.int64)
        validity = xp.ones((ctx.capacity,), dtype=bool)
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            ids = xp.where(validity, ids, 0)
        return ColV(DataType.INT64, ids, validity)


class SparkPartitionID(LeafExpression):
    @property
    def data_type(self):
        return DataType.INT32

    @property
    def nullable(self):
        return False

    def eval_kernel(self, ctx):
        xp = ctx.xp
        data = xp.full((ctx.capacity,), ctx.partition_id, dtype=np.int32)
        validity = xp.ones((ctx.capacity,), dtype=bool)
        if ctx.is_device:
            validity = validity & ctx.row_mask()
            data = xp.where(validity, data, 0)
        return ColV(DataType.INT32, data, validity)


class InputFileName(LeafExpression):
    """input_file_name(). Poisons batch coalescing upstream (reference:
    GpuExpression.disableCoalesceUntilInput) — handled by the transition
    optimizer. Round 1: evaluates to '' like Spark does outside scans."""

    @property
    def data_type(self):
        return DataType.STRING

    @property
    def nullable(self):
        return False

    @property
    def disable_coalesce_until_input(self) -> bool:
        return True

    def eval_kernel(self, ctx):
        from spark_rapids_tpu.ops.values import ScalarV

        return ScalarV(DataType.STRING, "")


class _InputFileBlockBase(LeafExpression):
    """input_file_block_start()/length(): -1 outside a scan context, like
    Spark when no file block is being read (reference:
    GpuInputFileBlockStart/Length, GpuOverrides.scala). Shares
    InputFileName's coalesce poisoning so the transition optimizer keeps
    the batch:file-block correspondence intact."""

    @property
    def data_type(self):
        return DataType.INT64

    @property
    def nullable(self):
        return False

    @property
    def disable_coalesce_until_input(self) -> bool:
        return True

    def eval_kernel(self, ctx):
        from spark_rapids_tpu.ops.values import ScalarV

        return ScalarV(DataType.INT64, -1)


class InputFileBlockStart(_InputFileBlockBase):
    pass


class InputFileBlockLength(_InputFileBlockBase):
    pass
