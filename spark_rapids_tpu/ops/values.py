"""Evaluation value model shared by the device (jnp) and cpu (numpy) paths.

Reference parity: GpuExpression.columnarEval returns either a GpuColumnVector
or a scalar (GpuExpressions.scala:74-99); GpuScalar wraps host values into
cudf Scalars (literals.scala:33). Here `ColV` is the column result and
`ScalarV` the scalar result; kernels receive either and rely on numpy/jnp
broadcasting.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType


@dataclass
class ColV:
    """A column value during evaluation.

    device path: data/validity (and offsets for strings) are traced jax arrays
    padded to the batch capacity.
    cpu path: numpy arrays of exactly num_rows; strings are object arrays and
    offsets is None.

    `vrange` (static (lo, hi) python ints, or None = unknown) bounds the
    valid values of an integral column; kernels use it to prove that int32
    compute is exact for a logically-int64 expression (columnar.batch
    module docstring). It is aux data in the jit pytree, so narrowability
    participates in program cache identity.
    """

    dtype: DataType
    data: Any
    validity: Any
    offsets: Optional[Any] = None
    vrange: Optional[tuple] = None
    # static pow2 bound on any single string's byte length (STRING only;
    # None = unknown) — see ColumnVector.max_len
    max_len: Optional[int] = None

    @property
    def is_string(self) -> bool:
        return self.dtype is DataType.STRING


@dataclass
class ScalarV:
    dtype: DataType
    value: Any  # python scalar; None iff is_null
    @property
    def is_null(self) -> bool:
        return self.value is None


class EvalContext:
    """Carries the batch being evaluated plus engine context.

    device path: xp = jax.numpy, capacity static, num_rows traced scalar.
    cpu path: xp = numpy, capacity == num_rows (no padding), num_rows int.
    """

    __slots__ = (
        "xp", "is_device", "columns", "num_rows", "capacity",
        "partition_id", "rng_seed", "row_start", "narrow", "ansi_errors",
    )

    def __init__(self, xp, is_device, columns, num_rows, capacity,
                 partition_id=0, rng_seed=0, row_start=0, narrow=True):
        # deferred ANSI error channel: device ops can't raise mid-trace, so
        # they append (device bool scalar, message) here and the evaluator
        # entry point (DeviceProjector/DeviceFilter) checks the flags after
        # the jitted call returns — one batched host read, zero cost when
        # no ANSI op is present
        self.ansi_errors = []
        self.xp = xp
        self.is_device = is_device
        # narrow=False turns int32 narrowing off for the WHOLE kernel:
        # inputs stay at physical width AND expression ops skip their
        # in-kernel narrowing (checked via ctx.narrow in _narrow_npdt)
        self.narrow = narrow
        if is_device and narrow:
            columns = [narrow_colv(cv) for cv in columns]
        self.columns = columns  # list[ColV]
        self.num_rows = num_rows
        self.capacity = capacity
        self.partition_id = partition_id
        self.rng_seed = rng_seed
        # global row offset of this batch within the partition (for
        # monotonically_increasing_id)
        self.row_start = row_start

    def row_mask(self):
        return self.xp.arange(self.capacity) < self.num_rows


def narrow_colv(cv: ColV) -> ColV:
    """int32 view of a logically-int64 column whose value range fits int32
    (exact: value-preserving; null/pad lanes hold zeros by convention and
    survive the cast unchanged). The astype fuses into the consuming kernel
    — XLA reads the int64 pair once and computes 32-bit thereafter."""
    from spark_rapids_tpu.columnar.batch import (
        fits_int32,
        int64_narrowing_enabled,
    )

    if (isinstance(cv, ColV) and cv.data is not None
            and cv.dtype is DataType.INT64 and fits_int32(cv.vrange)
            and int64_narrowing_enabled()
            and hasattr(cv.data, "astype")
            and np.dtype(cv.data.dtype).itemsize > 4):
        return ColV(cv.dtype, cv.data.astype(np.int32), cv.validity,
                    cv.offsets, cv.vrange, cv.max_len)
    return cv


def and_validity(xp, *validities):
    """Null propagation: result is null if any input is null."""
    out = None
    for v in validities:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


def broadcast_scalar(ctx: EvalContext, s: ScalarV):
    """Materialize a scalar as a column (used when a kernel needs arrays)."""
    xp = ctx.xp
    if s.dtype is DataType.STRING:
        raise NotImplementedError("string scalar broadcast is kernel-specific")
    npdt = s.dtype.to_np()
    vrange = None
    if ctx.is_device:
        from spark_rapids_tpu.columnar.batch import (
            fits_int32,
            int64_narrowing_enabled,
            physical_np_dtype,
        )

        npdt = physical_np_dtype(s.dtype)
        if s.dtype is DataType.INT64 and not s.is_null:
            vrange = (int(s.value), int(s.value))
            if (fits_int32(vrange) and int64_narrowing_enabled()
                    and getattr(ctx, "narrow", True)):
                npdt = np.dtype(np.int32)
    fill = s.value if not s.is_null else 0
    data = xp.full((ctx.capacity,), npdt.type(fill) if not ctx.is_device else fill,
                   dtype=npdt)
    validity = xp.full((ctx.capacity,), not s.is_null, dtype=bool)
    if ctx.is_device:
        validity = validity & ctx.row_mask()
    return ColV(s.dtype, data, validity, vrange=vrange)


def zero_nulls(xp, data, validity):
    """Re-establish the 'data is 0 at null slots' convention after a kernel
    (keeps padded/null lanes deterministic for hashing and sorting)."""
    if validity is None:
        return data
    return xp.where(validity, data, np.zeros((), dtype=data.dtype))
