"""Fixed-point decimal math over the int64 unscaled representation.

Result precision/scale rules follow Spark's DecimalPrecision coercion
(adapted to the 64-bit MAX_PRECISION=18 cap, i.e. Spark's
Decimal.MAX_LONG_DIGITS), and arithmetic overflow produces SQL NULL exactly
like Spark's non-ANSI mode. The reference's v0.1 plugin excludes decimals
from its type gate (GpuOverrides.scala:383-395); this goes beyond it to
cover BASELINE config 5 (window + decimal casts).

Every kernel here is xp-polymorphic (numpy oracle / jax.numpy device) and
uses only int64 ops — no floats — so device results are bit-identical to
the oracle. Overflow is *detected before it can wrap* (checked multiply via
magnitude bounds) and surfaces as a False validity lane.

Known deviation of the 64-bit subset: multiply/divide intermediates are
computed in int64 at the *natural* scale, so an operation whose final
(adjusted) result would fit can still return NULL when the intermediate
exceeds int64 — e.g. decimal(18,0) 10^13 / 10^4 scales the numerator by
10^6 past 2^63. Spark's 128-bit Decimal backing succeeds there. Lifting
this requires two-limb (hi/lo) multiply + long division; until then the
engine returns NULL rather than ever a wrong value.
"""

from __future__ import annotations

import decimal
from typing import Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import (
    DataType,
    DecimalType,
    INTEGRAL_DECIMAL_PRECISION,
)

INT64_MAX = np.int64(np.iinfo(np.int64).max)

# 10**k as int64 for k in [0, 18]
POW10 = [np.int64(10) ** np.int64(k) for k in range(19)]


def bound(precision: int) -> np.int64:
    """Largest unscaled magnitude representable at `precision` digits."""
    return np.int64(POW10[precision] - 1)


def as_decimal_type(dt) -> Optional[DecimalType]:
    """View a type as a decimal for mixed decimal/integral arithmetic
    (Spark DecimalPrecision: integral literals/columns widen to the exact
    decimal that holds the type)."""
    if isinstance(dt, DecimalType):
        return dt
    if dt in INTEGRAL_DECIMAL_PRECISION:
        return DecimalType(INTEGRAL_DECIMAL_PRECISION[dt], 0)
    return None


def _adjust(precision: int, scale: int) -> DecimalType:
    """Spark's DecimalType.adjustPrecisionScale for MAX=18: when the natural
    result precision overflows, sacrifice scale (down to min(scale, 6)) to
    preserve integral digits."""
    MAX = DecimalType.MAX_PRECISION
    if precision <= MAX:
        return DecimalType(max(precision, 1), scale)
    int_digits = precision - scale
    min_scale = min(scale, 6)
    adjusted_scale = max(MAX - int_digits, min_scale)
    return DecimalType(MAX, adjusted_scale)


# public name for Spark's DecimalType.bounded(p, s)
def bounded(precision: int, scale: int) -> DecimalType:
    return _adjust(precision, scale)


def add_result_type(l: DecimalType, r: DecimalType) -> DecimalType:
    s = max(l.scale, r.scale)
    p = max(l.precision - l.scale, r.precision - r.scale) + s + 1
    return _adjust(p, s)


def multiply_result_type(l: DecimalType, r: DecimalType) -> DecimalType:
    return _adjust(l.precision + r.precision + 1, l.scale + r.scale)


def divide_result_type(l: DecimalType, r: DecimalType) -> DecimalType:
    s = max(6, l.scale + r.precision + 1)
    p = l.precision - l.scale + r.scale + s
    return _adjust(p, s)


def remainder_result_type(l: DecimalType, r: DecimalType) -> DecimalType:
    s = max(l.scale, r.scale)
    p = min(l.precision - l.scale, r.precision - r.scale) + s
    return _adjust(p, s)


# ---------------------------------------------------------------------------
# Checked kernels (xp = numpy or jax.numpy); every function returns
# (data, ok_mask) with data zeroed where not ok.
# ---------------------------------------------------------------------------
def _i64(xp, v):
    if hasattr(v, "astype"):
        return v.astype(np.int64)
    return xp.asarray(v, dtype=np.int64) if xp is not None else np.int64(v)


def checked_mul_pow10(xp, data, k: int):
    """data * 10**k with overflow -> not ok. k is static per expression."""
    data = _i64(xp, data)
    if k <= 0:
        return data, xp.ones_like(data, dtype=bool)
    if k > 18:
        return xp.zeros_like(data), xp.zeros_like(data, dtype=bool)
    limit = INT64_MAX // POW10[k]
    ok = xp.abs(data) <= limit
    return xp.where(ok, data, 0) * POW10[k], ok


def checked_mul(xp, l, r):
    """l * r with wrap-free overflow detection via magnitude bound."""
    l = _i64(xp, l)
    r = _i64(xp, r)
    absr = xp.abs(r)
    # |l| > INT64_MAX // |r| implies the true product exceeds int64.
    safe_absr = xp.where(absr == 0, 1, absr)
    ok = (absr == 0) | (xp.abs(l) <= INT64_MAX // safe_absr)
    return xp.where(ok, l, 0) * r, ok


def div_half_up(xp, num, den):
    """Sign-aware ROUND_HALF_UP integer division (Spark's decimal rounding).
    den == 0 lanes return 0 with ok False."""
    num = _i64(xp, num)
    den = _i64(xp, den)
    ok = den != 0
    an = xp.abs(num)
    ad = xp.where(ok, xp.abs(den), 1)
    q = an // ad
    rem = an - q * ad
    # round half away from zero: bump when rem >= ad - rem  <=>  2*rem >= ad
    q = q + ((rem >= ad - rem) & (rem != 0)).astype(np.int64)
    neg = (num < 0) ^ (den < 0)
    return xp.where(ok, xp.where(neg, -q, q), 0), ok


def rescale(xp, data, from_scale: int, to_scale: int):
    """Change scale; scaling down rounds HALF_UP; scaling up checks
    overflow."""
    if to_scale == from_scale:
        data = _i64(xp, data)
        return data, xp.ones_like(data, dtype=bool)
    if to_scale > from_scale:
        return checked_mul_pow10(xp, data, to_scale - from_scale)
    k = from_scale - to_scale
    if k > 18:
        z = xp.zeros_like(_i64(xp, data))
        return z, xp.ones_like(z, dtype=bool)
    out, _ = div_half_up(xp, data, POW10[k])
    return out, xp.ones_like(out, dtype=bool)


def fit_precision(xp, data, precision: int):
    """ok where |data| fits in `precision` digits (overflow -> SQL NULL).
    Two-sided compare, NOT abs: np.abs(INT64_MIN) wraps negative, and an
    int64-wrapped intermediate landing exactly on -2^63 must be rejected."""
    b = bound(precision)
    ok = (data <= b) & (data >= -b)
    return xp.where(ok, data, 0), ok


def compare_rescale(xp, data, from_scale: int, to_scale: int):
    """Rescale for *comparison*: lanes whose rescaled magnitude would
    overflow saturate to +/-INT64_MAX, which preserves ordering (and
    inequality) against any in-range operand since every valid unscaled
    decimal is <= 10**18 - 1 < INT64_MAX."""
    data = _i64(xp, data)
    if to_scale <= from_scale:
        return data
    out, ok = checked_mul_pow10(xp, data, to_scale - from_scale)
    sat = xp.where(data < 0, -INT64_MAX, INT64_MAX)
    return xp.where(ok, out, sat)


# ---------------------------------------------------------------------------
# Host-side value conversion (literals, builders, collect)
# ---------------------------------------------------------------------------
def to_unscaled(value, scale: int, precision: Optional[int] = None) -> int:
    """Python value (Decimal/int/float/str) -> unscaled int at `scale`,
    rounding HALF_UP like Spark's Decimal.changePrecision. When `precision`
    is given, values beyond its digit bound are rejected (ingestion must
    never admit an unscaled value outside the bound every kernel relies
    on)."""
    if isinstance(value, decimal.Decimal):
        d = value
    elif isinstance(value, (int, np.integer)):
        d = decimal.Decimal(int(value))
    elif isinstance(value, (float, np.floating)):
        d = decimal.Decimal(repr(float(value)))
    elif isinstance(value, str):
        d = decimal.Decimal(value.strip())
    else:
        raise TypeError(f"cannot convert {value!r} to decimal")
    q = d.scaleb(scale).to_integral_value(rounding=decimal.ROUND_HALF_UP)
    i = int(q)
    if abs(i) > int(INT64_MAX):
        raise OverflowError(f"decimal {value} does not fit in 64 bits at "
                            f"scale {scale}")
    if precision is not None and abs(i) > int(bound(precision)):
        raise OverflowError(
            f"decimal {value} does not fit decimal({precision},{scale})")
    return i


def from_unscaled(unscaled: int, scale: int) -> decimal.Decimal:
    """Unscaled int -> decimal.Decimal (user-facing collect value)."""
    return decimal.Decimal(int(unscaled)).scaleb(-scale)


def infer_decimal_type(value) -> DecimalType:
    """DecimalType that exactly holds a python Decimal literal."""
    d = value if isinstance(value, decimal.Decimal) else \
        decimal.Decimal(str(value))
    t = d.as_tuple()
    scale = max(0, -t.exponent)
    digits = len(t.digits) + max(0, t.exponent)
    precision = max(digits, scale)
    MAX = DecimalType.MAX_PRECISION
    if precision > MAX or scale > MAX:
        raise ValueError(f"decimal literal {d} exceeds {MAX} digits")
    return DecimalType(max(precision, 1), scale)
