"""Conditional expressions (reference: conditionalExpressions.scala, 251 LoC —
if / case-when)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import Expression, TernaryExpression
from spark_rapids_tpu.ops.values import ColV, ScalarV, broadcast_scalar


def _cond_parts(ctx, v):
    """(is_true_data, ) for a boolean predicate value; null counts as false."""
    xp = ctx.xp
    if isinstance(v, ScalarV):
        truth = (not v.is_null) and bool(v.value)
        return xp.full((ctx.capacity,), truth)
    return v.data.astype(bool) & v.validity


def _merge_branch(ctx, pred_true, then_v, else_data, else_valid, dtype):
    xp = ctx.xp
    if dtype is DataType.STRING:
        raise AssertionError("string branches handled via string_select")
    if isinstance(then_v, ScalarV):
        then_v = broadcast_scalar(ctx, then_v)
    data = xp.where(pred_true, then_v.data, else_data)
    valid = xp.where(pred_true, then_v.validity, else_valid)
    return data, valid


class If(TernaryExpression):
    @property
    def data_type(self):
        return self.b.data_type if self.b.data_type is not DataType.NULL \
            else self.c.data_type

    def eval_kernel(self, ctx, pred, tv, fv):
        xp = ctx.xp
        if isinstance(pred, ScalarV) and isinstance(tv, ScalarV) and \
           isinstance(fv, ScalarV):
            taken = tv if ((not pred.is_null) and bool(pred.value)) else fv
            return ScalarV(self.data_type, taken.value)
        pred_true = _cond_parts(ctx, pred)
        if self.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            return S.string_select(ctx, pred_true, tv, fv)
        if isinstance(fv, ScalarV):
            fv = broadcast_scalar(ctx, fv)
        data, valid = _merge_branch(ctx, pred_true, tv, fv.data, fv.validity,
                                    self.data_type)
        if ctx.is_device:
            rm = ctx.row_mask()
            valid = valid & rm
            data = xp.where(valid, data, 0)
        return ColV(self.data_type, data, valid,
                    vrange=self.result_vrange(pred, tv, fv))

    def result_vrange(self, pred, tv, fv):
        from spark_rapids_tpu.columnar.batch import union_vrange
        from spark_rapids_tpu.ops.base import val_interval

        if not self.data_type.is_integral:
            return None
        return union_vrange(val_interval(tv), val_interval(fv))


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 [WHEN c2 THEN v2]... [ELSE e] END."""

    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        assert branches
        self.branches = tuple((c, v) for c, v in branches)
        self.else_value = else_value

    def children(self):
        out: List[Expression] = []
        for c, v in self.branches:
            out.extend((c, v))
        if self.else_value is not None:
            out.append(self.else_value)
        return tuple(out)

    def with_children(self, new_children):
        n = len(self.branches)
        branches = [(new_children[2 * i], new_children[2 * i + 1]) for i in range(n)]
        else_v = new_children[2 * n] if len(new_children) > 2 * n else None
        return CaseWhen(branches, else_v)

    @property
    def data_type(self):
        return self.branches[0][1].data_type

    @property
    def nullable(self):
        if self.else_value is None:
            return True
        return any(v.nullable for _, v in self.branches) or self.else_value.nullable

    def eval_kernel(self, ctx, *vals):
        xp = ctx.xp
        n = len(self.branches)
        conds = [vals[2 * i] for i in range(n)]
        thens = [vals[2 * i + 1] for i in range(n)]
        else_v = vals[2 * n] if len(vals) > 2 * n else ScalarV(self.data_type, None)

        if self.data_type is DataType.STRING:
            from spark_rapids_tpu.columnar import strings as S

            result = else_v
            for c, t in zip(reversed(conds), reversed(thens)):
                result = S.string_select(ctx, _cond_parts(ctx, c), t, result)
            return result

        if isinstance(else_v, ScalarV):
            else_col = broadcast_scalar(
                ctx, else_v if not else_v.is_null else ScalarV(self.data_type, None)
            )
        else:
            else_col = else_v
        data, valid = else_col.data, else_col.validity
        for c, t in zip(reversed(conds), reversed(thens)):
            pred_true = _cond_parts(ctx, c)
            data, valid = _merge_branch(ctx, pred_true, t, data, valid,
                                        self.data_type)
        if ctx.is_device:
            rm = ctx.row_mask()
            valid = valid & rm
            data = xp.where(valid, data, 0)
        vrange = None
        if self.data_type.is_integral:
            from spark_rapids_tpu.columnar.batch import union_vrange
            from spark_rapids_tpu.ops.base import val_interval

            ivs = [val_interval(t) for t in thens]
            if not (isinstance(else_v, ScalarV) and else_v.is_null):
                ivs.append(val_interval(else_v))
            vrange = union_vrange(*ivs)
        return ColV(self.data_type, data, valid, vrange=vrange)
