"""Projection/filter evaluation entry points.

Device path: the whole bound-expression list is traced into ONE jit program
per (expression fingerprint, batch shape bucket) — XLA fuses the expression
tree the way cuDF evaluates per-op kernels back-to-back (but better: one
fused kernel, no intermediate materialization in HBM unless XLA decides to).

CPU path: the same expression trees evaluate with numpy — the independent
oracle/fallback engine.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import _jax_setup  # noqa: F401
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.batch import (
    ColumnVector,
    ColumnarBatch,
    HostColumnVector,
    HostColumnarBatch,
)
from spark_rapids_tpu.engine.retry import with_retry
from spark_rapids_tpu.ops.base import Expression
from spark_rapids_tpu.ops.values import ColV, EvalContext, ScalarV, broadcast_scalar
from spark_rapids_tpu.utils import metrics as M

# ColV must flow through jit as a pytree (vrange rides the aux data so
# narrowability is part of program cache identity)
jax.tree_util.register_pytree_node(
    ColV,
    lambda cv: (
        ((cv.data, cv.validity, cv.offsets),
         (cv.dtype, True, cv.vrange, cv.max_len))
        if cv.offsets is not None
        else ((cv.data, cv.validity), (cv.dtype, False, cv.vrange, None))
    ),
    lambda aux, ch: ColV(aux[0], ch[0], ch[1], ch[2] if aux[1] else None,
                         vrange=aux[2], max_len=aux[3]),
)


def _col_to_colv(cv: ColumnVector) -> ColV:
    from spark_rapids_tpu.columnar.encoded import is_encoded

    if is_encoded(cv):
        # an encoded column must NEVER reach a value kernel as raw codes —
        # that would silently compute on dictionary indices. Operators
        # either keep it in code space deliberately (encoded.codes_colv)
        # or decode it visibly (encoded.materialize / decode_batch).
        raise TypeError(
            "encoded DictionaryColumn reached a kernel boundary without "
            "materialize(); route through columnar.encoded helpers")
    return ColV(cv.dtype, cv.data, cv.validity, cv.offsets,
                vrange=cv.vrange, max_len=cv.max_len)


def _colv_to_col(cv: ColV) -> ColumnVector:
    return ColumnVector(cv.dtype, cv.data, cv.validity, cv.offsets,
                        vrange=cv.vrange, max_len=cv.max_len)


def _widen_physical(cv: ColV) -> ColV:
    """Restore storage physical dtype at a kernel boundary: batches in HBM
    keep the physical_np_dtype invariant (int64 for LONG) so every consumer
    — serde, shuffle slicing, window scans, export — stays oblivious to
    in-kernel narrowing; vrange survives so the NEXT kernel re-narrows."""
    from spark_rapids_tpu.columnar.batch import physical_np_dtype

    if cv.dtype is DataType.STRING or not hasattr(cv.data, "astype"):
        return cv
    npdt = physical_np_dtype(cv.dtype)
    if cv.data.dtype == npdt:
        return cv
    return ColV(cv.dtype, cv.data.astype(npdt), cv.validity, cv.offsets,
                vrange=cv.vrange)


def _scalar_to_colv(ctx: EvalContext, s: ScalarV, want: DataType) -> ColV:
    if want is DataType.STRING or s.dtype is DataType.STRING:
        from spark_rapids_tpu.columnar import strings as S

        # materialize one copy of the scalar bytes per row (string literal
        # lengths are known at trace time, so byte_cap stays static)
        v = S.as_view(ctx, s)
        n = 0 if s.is_null else len(s.value.encode("utf-8"))
        byte_cap = max(8, ctx.capacity * max(n, 1))
        validity = v.validity & ctx.row_mask()
        lens = jnp.where(validity, n, 0)
        data, offsets = S.build_from_plan(
            [v.data], jnp.zeros((ctx.capacity,), jnp.int32),
            jnp.zeros((ctx.capacity,), jnp.int32), lens, byte_cap)
        return ColV(DataType.STRING, data, validity, offsets)
    if s.dtype is DataType.NULL:
        s = ScalarV(want, None)
    col = broadcast_scalar(ctx, s)
    return ColV(want, col.data, col.validity)


def keep_mask_from_result(r, capacity: int):
    """Boolean keep mask from a filter condition's evaluated result:
    a scalar condition keeps all or no rows; a column keeps rows whose
    value is true AND non-null (SQL: null condition drops the row).
    Shared by DeviceFilter and the fused-stage program (exec/fused.py) so
    the two paths can never diverge on null semantics."""
    if isinstance(r, ScalarV):
        return jnp.full((capacity,), (not r.is_null) and bool(r.value))
    return r.data.astype(bool) & r.validity


def raise_deferred_ansi(flags, msgs) -> None:
    """Drain the deferred ANSI error channel after a jitted call (one
    batched host read; zero cost when no ANSI op traced)."""
    if not flags:
        return
    # tpulint: host-sync -- one batched flag read, only when ANSI ops traced
    got = jax.device_get(flags)
    for v, m in zip(got, msgs):
        if bool(v):
            raise ValueError(m)


class DeviceProjector:
    """Compiles and caches the jitted evaluator for a fixed list of bound
    expressions (reference: GpuProjectExec's bound-expression evaluation,
    basicPhysicalOperators.scala:34-95).

    Encoded inputs (columnar.encoded.DictionaryColumn) stay encoded where
    the projection allows it: a bare-reference output passes the encoded
    column through untouched, code-space-supported predicates over it
    rewrite their literals into codes, and only columns a computed
    expression genuinely needs the VALUES of decode — visibly, through
    materialize()."""

    def __init__(self, exprs: Sequence[Expression]):
        self.exprs = list(exprs)
        self._jitted = None
        self._enc_plans: dict = {}

    def _build_for(self, exprs):
        from spark_rapids_tpu.engine.jit_cache import get_or_build

        exprs = list(exprs)
        key = ("project", tuple(e.fingerprint() for e in exprs))

        def build():
            msgs: List[str] = []

            def fn(cols: List[ColV], num_rows, partition_id, row_start):
                capacity = cols[0].validity.shape[0] if cols else 8
                ctx = EvalContext(jnp, True, cols, num_rows, capacity,
                                  partition_id=partition_id,
                                  row_start=row_start)
                outs = []
                for e in exprs:
                    r = e.eval(ctx)
                    if isinstance(r, ScalarV):
                        r = _scalar_to_colv(ctx, r, e.data_type)
                    outs.append(_widen_physical(r))
                # deferred ANSI flags surface as extra outputs; messages are
                # trace-static and rebuilt on every (re)trace
                del msgs[:]
                msgs.extend(m for _, m in ctx.ansi_errors)
                return outs, [f for f, _ in ctx.ansi_errors]

            return jax.jit(fn), msgs

        return get_or_build(key, build)

    def _dispatch(self, jitted, msgs, cols, batch, partition_id, row_start):
        if not cols:
            # zero-column input (e.g. COUNT(*) over bare scan): evaluate with a
            # synthetic capacity derived from num_rows
            from spark_rapids_tpu.columnar.batch import bucket_capacity

            cap = bucket_capacity(max(batch.host_rows(), 1))
            # tpulint: eager-jnp, untracked-alloc -- zero-column COUNT(*)
            # placeholder col: one tiny bool lane, not batch data
            cols = [ColV(DataType.BOOL,
                         jnp.zeros((cap,), dtype=bool),
                         jnp.arange(cap) < batch.num_rows)]
        n = jnp.asarray(batch.num_rows, dtype=jnp.int32)

        def _attempt():
            M.record_dispatch()
            outs, flags = jitted(cols, n, jnp.int32(partition_id),
                                 jnp.int64(row_start))
            raise_deferred_ansi(flags, msgs)
            return outs

        return with_retry(_attempt, site="project")

    def project(self, batch: ColumnarBatch, partition_id: int = 0,
                row_start: int = 0) -> ColumnarBatch:
        from spark_rapids_tpu.columnar import encoded as ENC

        if ENC.encoded_ordinals(batch):
            return self._project_encoded(batch, partition_id, row_start)
        if self._jitted is None:
            self._jitted = self._build_for(self.exprs)
        jitted, msgs = self._jitted
        cols = [_col_to_colv(c) for c in batch.columns]
        outs = self._dispatch(jitted, msgs, cols, batch, partition_id,
                              row_start)
        return ColumnarBatch([_colv_to_col(o) for o in outs], batch.num_rows)

    def _enc_plan(self, batch):
        """(passthrough map, rewritten eval exprs, code ords, mat ords):
        cached per (ordinal, dictionary) signature of the batch."""
        from spark_rapids_tpu.columnar import encoded as ENC
        from spark_rapids_tpu.ops.base import Alias, BoundReference

        sig = ENC.enc_sig(batch)
        plan = self._enc_plans.get(sig)
        if plan is not None:
            return plan
        enc = {i: c for i, c in enumerate(batch.columns)
               if ENC.is_encoded(c)}

        def pass_ord(e):
            inner = e.child if isinstance(e, Alias) else e
            if isinstance(inner, BoundReference) and inner.ordinal in enc:
                return inner.ordinal
            return None

        passthrough = {oi: pass_ord(e) for oi, e in enumerate(self.exprs)
                       if pass_ord(e) is not None}
        eval_exprs = [e for oi, e in enumerate(self.exprs)
                      if oi not in passthrough]
        ok, rank = ENC.classify_bound_refs(eval_exprs, enc.keys())
        referenced = set()
        for e in eval_exprs:
            referenced |= ENC._bound_ref_ords(e)
        mat = tuple(sorted((set(enc) - ok) & referenced))
        dict_by_ord = {i: (enc[i].dictionary.sorted_dict() if i in rank
                           else enc[i].dictionary) for i in ok}
        rewritten = [ENC.rewrite_bound_condition(e, dict_by_ord)
                     if dict_by_ord else e for e in eval_exprs]
        # the trailing one-slot list caches the built jit handle so the
        # expression trees are fingerprinted once per signature, not per
        # batch (_project_encoded fills it on first dispatch)
        plan = (passthrough, rewritten, frozenset(ok), frozenset(rank),
                mat, [None])
        self._enc_plans[sig] = plan
        if len(self._enc_plans) > 64:
            self._enc_plans.pop(next(iter(self._enc_plans)))
        return plan

    def _project_encoded(self, batch, partition_id, row_start):
        from spark_rapids_tpu.columnar import encoded as ENC

        passthrough, rewritten, code_ords, rank_ords, mat, built = \
            self._enc_plan(batch)
        # tpulint: eager-materialize -- projection expressions outside
        # the code-space subset need values; passthroughs stay codes
        batch = ENC.batch_with_materialized(batch, mat)
        batch = ENC.batch_to_rank_space(batch, rank_ords)
        outs: List = [None] * len(self.exprs)
        if rewritten:
            cols = ENC.eval_cols(batch, code_ords)
            if built[0] is None:
                built[0] = self._build_for(rewritten)
            jitted, msgs = built[0]
            evaluated = self._dispatch(jitted, msgs, cols, batch,
                                       partition_id, row_start)
            ei = iter(evaluated)
            for oi in range(len(self.exprs)):
                if oi not in passthrough:
                    outs[oi] = _colv_to_col(next(ei))
        for oi, ord_ in passthrough.items():
            outs[oi] = batch.columns[ord_]
        return ColumnarBatch(outs, batch.num_rows)


class DeviceFilter:
    """Filter: evaluate the boolean condition inside jit, compact outside
    (the row-count host sync; reference: GpuFilterExec + cudf Table.filter)."""

    def __init__(self, condition: Expression):
        self.condition = condition
        self._jitted = None
        self._enc_jitted: dict = {}
        self._enc_plans: dict = {}

    def _build_for(self, cond):
        from spark_rapids_tpu.engine.jit_cache import get_or_build

        key = ("filter", cond.fingerprint())

        def build():
            msgs = []

            def fn(cols, num_rows, partition_id, row_start):
                capacity = cols[0].validity.shape[0]
                ctx = EvalContext(jnp, True, cols, num_rows, capacity,
                                  partition_id=partition_id,
                                  row_start=row_start)
                keep = keep_mask_from_result(cond.eval(ctx), capacity)
                del msgs[:]
                msgs.extend(m for _, m in ctx.ansi_errors)
                return keep & ctx.row_mask(), [f for f, _ in ctx.ansi_errors]

            return jax.jit(fn), msgs

        return get_or_build(key, build)

    def apply(self, batch: ColumnarBatch, partition_id: int = 0,
              row_start: int = 0, lazy: bool = False) -> ColumnarBatch:
        from spark_rapids_tpu.columnar import encoded as ENC
        from spark_rapids_tpu.columnar.batch import compact_batch

        # plan memoized per encoded signature: the sig fully determines
        # the rewrite (interned dictionaries), so the supported-refs
        # walks + condition-tree rebuild run once per dictionary set
        ekey = ENC.enc_sig(batch)
        if ekey in self._enc_plans:
            plan = self._enc_plans[ekey]
        else:
            plan = ENC.plan_filter(self.condition, batch)
            self._enc_plans[ekey] = plan
            while len(self._enc_plans) > 64:
                self._enc_plans.pop(next(iter(self._enc_plans)))
        if plan is None:
            if self._jitted is None:
                self._jitted = self._build_for(self.condition)
            jitted, msgs = self._jitted
            cols = [_col_to_colv(c) for c in batch.columns]
        else:
            # code-space filter: supported predicates over encoded columns
            # compare int32 codes against pre-translated literal codes —
            # ORDER comparisons first re-encode the column through the
            # sorted dictionary so the literal's rank threshold splits
            # code space exactly; unsupported uses decode (visible
            # materialize). The surviving rows compact WITH their codes —
            # the output batch stays encoded.
            # tpulint: eager-materialize -- non-code-space predicates over
            # the column need values; supported ordinals stay codes
            batch = ENC.batch_with_materialized(batch, plan.mat_ords)
            batch = ENC.batch_to_rank_space(batch, plan.rank_ords)
            built = self._enc_jitted.get(plan.sig)
            if built is None:
                built = self._enc_jitted[plan.sig] = \
                    self._build_for(plan.condition)
                while len(self._enc_jitted) > 64:
                    self._enc_jitted.pop(next(iter(self._enc_jitted)))
            jitted, msgs = built
            cols = ENC.eval_cols(batch, plan.code_ords)

        def _attempt():
            M.record_dispatch()
            keep, flags = jitted(cols, jnp.int32(batch.num_rows),
                                 jnp.int32(partition_id),
                                 jnp.int64(row_start))
            raise_deferred_ansi(flags, msgs)
            return keep

        keep = with_retry(_attempt, site="filter")
        return compact_batch(batch, keep, lazy=lazy)


# ---------------------------------------------------------------------------
# CPU oracle path
# ---------------------------------------------------------------------------
def _host_to_colv(hc: HostColumnVector) -> ColV:
    return ColV(hc.dtype, hc.data, hc.validity)


def _colv_to_host(cv: ColV, dtype: DataType) -> HostColumnVector:
    data = cv.data
    if dtype is DataType.STRING:
        if data.dtype != object:
            data = data.astype(object)
        data = np.where(cv.validity, data, "")
        return HostColumnVector(dtype, data, np.asarray(cv.validity, dtype=bool))
    npdt = dtype.to_np()
    if data.dtype != npdt:
        data = data.astype(npdt)
    data = np.where(cv.validity, data, npdt.type(0))
    return HostColumnVector(dtype, data, np.asarray(cv.validity, dtype=bool))


def cpu_eval_context(batch: HostColumnarBatch, partition_id: int = 0,
                     row_start: int = 0) -> EvalContext:
    cols = [_host_to_colv(c) for c in batch.columns]
    n = batch.num_rows
    return EvalContext(np, False, cols, n, n, partition_id=partition_id,
                       row_start=row_start)


def cpu_project(exprs: Sequence[Expression], batch: HostColumnarBatch,
                partition_id: int = 0, row_start: int = 0) -> HostColumnarBatch:
    ctx = cpu_eval_context(batch, partition_id, row_start)
    outs = []
    for e in exprs:
        r = e.eval(ctx)
        if isinstance(r, ScalarV):
            if e.data_type is DataType.STRING or r.dtype is DataType.STRING:
                data = np.full((ctx.capacity,),
                               r.value if not r.is_null else "", dtype=object)
                validity = np.full((ctx.capacity,), not r.is_null, dtype=bool)
                outs.append(HostColumnVector(DataType.STRING, data, validity))
                continue
            r = broadcast_scalar(ctx, ScalarV(e.data_type, r.value))
        outs.append(_colv_to_host(r, e.data_type))
    return HostColumnarBatch(outs, batch.num_rows)


def cpu_filter(condition: Expression, batch: HostColumnarBatch,
               partition_id: int = 0, row_start: int = 0) -> HostColumnarBatch:
    ctx = cpu_eval_context(batch, partition_id, row_start)
    r = condition.eval(ctx)
    if isinstance(r, ScalarV):
        keep = np.full((batch.num_rows,), (not r.is_null) and bool(r.value))
    else:
        keep = np.asarray(r.data, dtype=bool) & r.validity
    cols = [
        HostColumnVector(c.dtype, c.data[keep], c.validity[keep])
        for c in batch.columns
    ]
    return HostColumnarBatch(cols, int(keep.sum()))
