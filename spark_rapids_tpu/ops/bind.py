"""Reference binding (reference: GpuBindReferences.bindReference,
GpuBoundAttribute.scala:24-89 — rewrites AttributeReferences into
ordinal-indexed BoundReferences against the child's output schema)."""

from __future__ import annotations

from typing import List, Sequence

from spark_rapids_tpu.ops.base import (
    AttributeReference,
    BoundReference,
    Expression,
    SortOrder,
)


def bind_references(expr: Expression,
                    input_attrs: Sequence[AttributeReference]) -> Expression:
    id_to_ordinal = {a.expr_id: i for i, a in enumerate(input_attrs)}
    name_to_ordinal = {}
    for i, a in enumerate(input_attrs):
        name_to_ordinal.setdefault(a.name, i)

    def rewrite(node: Expression) -> Expression:
        if isinstance(node, AttributeReference):
            ordinal = id_to_ordinal.get(node.expr_id)
            if ordinal is None:
                ordinal = name_to_ordinal.get(node.name)
            if ordinal is None:
                raise KeyError(
                    f"cannot bind {node!r}; input attrs: {list(input_attrs)}"
                )
            return BoundReference(ordinal, node.data_type, node.nullable)
        return node

    return expr.transform_up(rewrite)


def bind_all(exprs: Sequence[Expression],
             input_attrs: Sequence[AttributeReference]) -> List[Expression]:
    return [bind_references(e, input_attrs) for e in exprs]


def bind_sort_orders(orders: Sequence[SortOrder],
                     input_attrs: Sequence[AttributeReference]) -> List[SortOrder]:
    return [
        SortOrder(bind_references(o.child, input_attrs), o.ascending, o.nulls_first)
        for o in orders
    ]
