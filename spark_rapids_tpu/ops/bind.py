"""Reference binding (reference: GpuBindReferences.bindReference,
GpuBoundAttribute.scala:24-89 — rewrites AttributeReferences into
ordinal-indexed BoundReferences against the child's output schema)."""

from __future__ import annotations

from typing import List, Sequence

from spark_rapids_tpu.ops.base import (
    AttributeReference,
    BoundReference,
    Expression,
    SortOrder,
)


def bind_references(expr: Expression,
                    input_attrs: Sequence[AttributeReference]) -> Expression:
    id_to_ordinal = {a.expr_id: i for i, a in enumerate(input_attrs)}
    name_to_ordinal = {}
    for i, a in enumerate(input_attrs):
        name_to_ordinal.setdefault(a.name, i)

    def rewrite(node: Expression) -> Expression:
        if isinstance(node, AttributeReference):
            ordinal = id_to_ordinal.get(node.expr_id)
            if ordinal is None:
                ordinal = name_to_ordinal.get(node.name)
            if ordinal is None:
                raise KeyError(
                    f"cannot bind {node!r}; input attrs: {list(input_attrs)}"
                )
            return BoundReference(ordinal, node.data_type, node.nullable)
        return node

    return expr.transform_up(rewrite)


def bind_all(exprs: Sequence[Expression],
             input_attrs: Sequence[AttributeReference]) -> List[Expression]:
    return [bind_references(e, input_attrs) for e in exprs]


def bind_sort_orders(orders: Sequence[SortOrder],
                     input_attrs: Sequence[AttributeReference]) -> List[SortOrder]:
    return [
        SortOrder(bind_references(o.child, input_attrs), o.ascending, o.nulls_first)
        for o in orders
    ]


def static_vrange(expr: Expression, col_vranges: Sequence):
    """Best-effort static (lo, hi) bound of a BOUND integral expression given
    per-ordinal input column bounds, evaluated symbolically via the same
    `result_vrange` interval rules the kernels use (no data touched). Used to
    re-attach value ranges to batches that cross a jit boundary as raw
    arrays (e.g. aggregate intermediate key columns), so downstream kernels
    keep the int32-narrowing proof (columnar.batch module docstring)."""
    from spark_rapids_tpu.ops.base import Alias
    from spark_rapids_tpu.ops.literals import Literal
    from spark_rapids_tpu.ops.values import ColV, ScalarV

    def rec(e):
        if isinstance(e, BoundReference):
            vr = col_vranges[e.ordinal] if e.ordinal < len(col_vranges) \
                else None
            return ColV(e.data_type, None, None, vrange=vr)
        if isinstance(e, Alias):
            return rec(e.child)
        if isinstance(e, Literal):
            return ScalarV(e.data_type, e.value)
        vals = [rec(c) for c in e.children()]
        try:
            vr = e.result_vrange(*vals)
        except Exception:
            vr = None
        return ColV(e.data_type, None, None, vrange=vr)

    from spark_rapids_tpu.columnar.batch import quantize_vrange

    out = rec(expr)
    # quantized: the result becomes batch-level aux data (jit cache key)
    return quantize_vrange(out.vrange) if isinstance(out, ColV) else None
