"""Expression layer (reference: sql-plugin layer 4 — GpuExpressions.scala,
GpuCast.scala, literals.scala, and the per-category expression files under
org/apache/spark/sql/rapids/).

Every expression has two evaluation paths:
- device: builds a jax-traceable computation over padded columns (the cuDF
  kernel analog); whole projections/filters are jit-compiled per capacity
  bucket.
- cpu: an independent numpy implementation with identical SQL null
  semantics — the CPU-fallback engine and the equivalence-test oracle
  (the role CPU Spark plays for the reference).
"""

from spark_rapids_tpu.ops.base import (  # noqa: F401
    AttributeReference,
    BoundReference,
    Alias,
    Expression,
    SortOrder,
)
from spark_rapids_tpu.ops.literals import Literal  # noqa: F401
from spark_rapids_tpu.ops.bind import bind_references  # noqa: F401
