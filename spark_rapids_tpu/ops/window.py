"""Window expressions (reference: GpuWindowExpression.scala, 723 LoC).

Reference parity:
- `GpuWindowSpecDefinition` (partition/order/frame, :390) -> `WindowSpec`.
- row/range frames with boundary checks (:457-683) -> `WindowFrame`
  (UNBOUNDED PRECEDING..CURRENT ROW default for ordered specs, matching
  Spark; ROWS offsets supported for prefix-sum-able aggregates).
- `GpuRowNumber` (:708) + rank/dense_rank/lag/lead -> ranking functions.
- aggregate-over-window via the same AggregateFunction objects the groupby
  uses (GpuWindowExpression eval via cudf window aggregation :87-235) ->
  the exec lowers them onto segmented prefix scans instead of cudf's
  windowed reductions.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.aggregates import AggregateFunction
from spark_rapids_tpu.ops.base import Expression, LeafExpression, SortOrder

UNBOUNDED = None  # frame boundary sentinel
CURRENT_ROW = 0


class WindowFrame:
    """(frame_type, lower, upper): lower/upper are row/range offsets,
    None = unbounded. ROW frame offsets are ints (negative = preceding)."""

    __slots__ = ("frame_type", "lower", "upper")

    def __init__(self, frame_type: str, lower, upper):
        assert frame_type in ("rows", "range")
        self.frame_type = frame_type
        self.lower = lower
        self.upper = upper

    @property
    def is_unbounded_to_current(self) -> bool:
        return self.lower is UNBOUNDED and self.upper == CURRENT_ROW

    @property
    def is_unbounded_both(self) -> bool:
        return self.lower is UNBOUNDED and self.upper is UNBOUNDED

    def fingerprint(self):
        return f"{self.frame_type}:{self.lower}:{self.upper}"

    def __repr__(self):
        def b(v, side):
            if v is UNBOUNDED:
                return f"UNBOUNDED {side}"
            if v == 0:
                return "CURRENT ROW"
            return f"{abs(v)} {'PRECEDING' if v < 0 else 'FOLLOWING'}"

        return (f"{self.frame_type.upper()} BETWEEN {b(self.lower, 'PRECEDING')} "
                f"AND {b(self.upper, 'FOLLOWING')}")


class WindowSpec:
    __slots__ = ("partition_by", "order_by", "frame")

    def __init__(self, partition_by: Sequence[Expression] = (),
                 order_by: Sequence[SortOrder] = (),
                 frame: Optional[WindowFrame] = None):
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        if frame is None:
            # Spark default: whole partition if unordered, else
            # RANGE UNBOUNDED PRECEDING .. CURRENT ROW
            frame = WindowFrame("range", UNBOUNDED, UNBOUNDED) \
                if not self.order_by else \
                WindowFrame("range", UNBOUNDED, CURRENT_ROW)
        self.frame = frame

    def fingerprint(self):
        return (f"W([{','.join(e.fingerprint() for e in self.partition_by)}],"
                f"[{','.join(o.fingerprint() for o in self.order_by)}],"
                f"{self.frame.fingerprint()})")

    def __repr__(self):
        return (f"Window(partitionBy={self.partition_by!r}, "
                f"orderBy={self.order_by!r}, {self.frame!r})")


class WindowFunction(LeafExpression):
    """Ranking/offset functions valid only inside a window."""

    @property
    def nullable(self):
        return False

    def eval_kernel(self, ctx):
        raise RuntimeError("window functions evaluate via the window exec")


class RowNumber(WindowFunction):
    @property
    def data_type(self):
        return DataType.INT32


class Rank(WindowFunction):
    @property
    def data_type(self):
        return DataType.INT32


class DenseRank(WindowFunction):
    @property
    def data_type(self):
        return DataType.INT32


class NTile(WindowFunction):
    def __init__(self, n: int):
        self.n = n

    @property
    def data_type(self):
        return DataType.INT32

    def _fingerprint_extra(self):
        return f"{self.n};"


class Lag(Expression):
    def __init__(self, child: Expression, offset: int = 1, default=None):
        self.child = child
        self.offset = offset
        self.default = default

    def children(self):
        return (self.child,)

    def with_children(self, new_children):
        return Lag(new_children[0], self.offset, self.default)

    @property
    def data_type(self):
        return self.child.data_type

    @property
    def nullable(self):
        return True

    def _fingerprint_extra(self):
        return f"{self.offset};{self.default!r};"

    def eval_kernel(self, ctx, v):
        raise RuntimeError("lag evaluates via the window exec")


class Lead(Lag):
    def with_children(self, new_children):
        return Lead(new_children[0], self.offset, self.default)


class WindowExpression(Expression):
    """function OVER spec. `function` is an AggregateFunction, a
    WindowFunction, or Lag/Lead."""

    def __init__(self, function: Expression, spec: WindowSpec):
        self.function = function
        self.spec = spec

    def children(self):
        # Spec expressions ARE children: analysis/transform machinery must
        # resolve partition/order columns (e.g. `Window.partitionBy("k")`
        # arrives as an unresolved name) just like the function input.
        return (self.function, *self.spec.partition_by,
                *[o.child for o in self.spec.order_by])

    def with_children(self, new_children):
        n_part = len(self.spec.partition_by)
        function = new_children[0]
        part = list(new_children[1:1 + n_part])
        orders = [
            SortOrder(c, o.ascending, o.nulls_first)
            for c, o in zip(new_children[1 + n_part:], self.spec.order_by)
        ]
        return WindowExpression(
            function, WindowSpec(part, orders, self.spec.frame))

    @property
    def data_type(self):
        if isinstance(self.function, AggregateFunction):
            return self.function.data_type
        return self.function.data_type

    @property
    def nullable(self):
        return True

    def _fingerprint_extra(self):
        return self.spec.fingerprint() + ";"

    def eval_kernel(self, ctx, *vals):
        raise RuntimeError("window expressions evaluate via the window exec")

    def __repr__(self):
        return f"{self.function!r} OVER {self.spec!r}"
