"""Date/time expressions (reference: datetimeExpressions.scala, 531 LoC —
year/month/day/hour/min/sec, datediff, unix_timestamp family, last_day,
from_unixtime). UTC only, like the reference's timestamp restriction.

Calendar math is Howard Hinnant's civil-from-days algorithm — pure integer
ops, identical results in numpy and jnp.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import BinaryExpression, UnaryExpression, _d
from spark_rapids_tpu.ops.cast import MICROS_PER_DAY, MICROS_PER_SEC


def civil_from_days(xp, z):
    """epoch days -> (year, month, day); valid over +-many millennia."""
    z = z + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = xp.where(mp < 10, mp + 3, mp - 9)
    y = y + (m <= 2)
    return y.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


def days_from_civil(xp, y, m, d):
    """(year, month, day) -> epoch days (inverse of civil_from_days)."""
    y = y.astype(np.int64) - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(np.int32)


def _days_of(ctx, v, dtype: DataType):
    if dtype is DataType.DATE:
        return v.data.astype(np.int64)
    return v.data // MICROS_PER_DAY


def _i32(x):
    """Cast an array or python scalar to int32."""
    return x.astype(np.int32) if hasattr(x, "astype") else np.int32(x)


class _DatePart(UnaryExpression):
    _part = None

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        xp = ctx.xp
        days = _days_of(ctx, v, self.child.data_type)
        y, m, d = civil_from_days(xp, days)
        return {"year": y, "month": m, "day": d}[self._part]


class Year(_DatePart):
    _part = "year"


class Month(_DatePart):
    _part = "month"


class DayOfMonth(_DatePart):
    _part = "day"


class _TimePart(UnaryExpression):
    _div = 1
    _mod = 1

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        micros = v.data
        sec_of_day = (micros % MICROS_PER_DAY) // MICROS_PER_SEC
        return ((sec_of_day // self._div) % self._mod).astype(np.int32)


class Hour(_TimePart):
    _div = 3600
    _mod = 24


class Minute(_TimePart):
    _div = 60
    _mod = 60


class Second(_TimePart):
    _div = 1
    _mod = 60


class DateDiff(BinaryExpression):
    """datediff(end, start) in days."""

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, lv, rv):
        return _i32(_d(lv)) - _i32(_d(rv))


class DateAdd(BinaryExpression):
    """date_add(start, days)."""

    @property
    def data_type(self):
        return DataType.DATE

    def do_columnar(self, ctx, lv, rv):
        return _i32(_d(lv)) + _i32(_d(rv))


class DateSub(BinaryExpression):
    @property
    def data_type(self):
        return DataType.DATE

    def do_columnar(self, ctx, lv, rv):
        return _i32(_d(lv)) - _i32(_d(rv))


class LastDay(UnaryExpression):
    """Last day of the month of the given date."""

    @property
    def data_type(self):
        return DataType.DATE

    def do_columnar(self, ctx, v):
        xp = ctx.xp
        y, m, _ = civil_from_days(xp, v.data.astype(np.int64))
        ny = xp.where(m == 12, y + 1, y)
        nm = xp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(xp, ny, nm, xp.ones_like(nm))
        return (first_next - 1).astype(np.int32)


class UnixTimestamp(UnaryExpression):
    """unix_timestamp(ts) -> epoch seconds (gated by improvedTimeOps conf for
    non-default formats, like the reference RapidsConf.scala:342)."""

    @property
    def data_type(self):
        return DataType.INT64

    def do_columnar(self, ctx, v):
        if self.child.data_type is DataType.DATE:
            return v.data.astype(np.int64) * 86_400
        return v.data // MICROS_PER_SEC


class ToUnixTimestamp(UnixTimestamp):
    """to_unix_timestamp(ts) — same device kernel as unix_timestamp
    (reference registers both names over one implementation,
    GpuOverrides.scala expr[ToUnixTimestamp]/expr[UnixTimestamp])."""


class FromUnixTime(UnaryExpression):
    """from_unixtime(sec) -> timestamp (default format path only)."""

    @property
    def data_type(self):
        return DataType.TIMESTAMP

    def do_columnar(self, ctx, v):
        return v.data.astype(np.int64) * MICROS_PER_SEC


class DayOfWeek(UnaryExpression):
    """1 = Sunday .. 7 = Saturday (Spark semantics)."""

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        days = _days_of(ctx, v, self.child.data_type)
        # 1970-01-01 was a Thursday (dow=5 in Spark's 1=Sunday scheme)
        return ((days + 4) % 7 + 1).astype(np.int32)


class WeekDay(UnaryExpression):
    """0 = Monday .. 6 = Sunday (Spark weekday(); reference
    datetimeExpressions.scala GpuWeekDay)."""

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        days = _days_of(ctx, v, self.child.data_type)
        # 1970-01-01 was a Thursday (weekday=3 in the 0=Monday scheme)
        return ((days + 3) % 7).astype(np.int32)


class DayOfYear(UnaryExpression):
    """1-based ordinal day within the year (reference:
    datetimeExpressions.scala GpuDayOfYear)."""

    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        xp = ctx.xp
        days = _days_of(ctx, v, self.child.data_type).astype(np.int64)
        y, _m, _d = civil_from_days(xp, days)
        jan1 = days_from_civil(xp, y, xp.ones_like(y), xp.ones_like(y))
        return (days - jan1 + 1).astype(np.int32)


class Quarter(UnaryExpression):
    @property
    def data_type(self):
        return DataType.INT32

    def do_columnar(self, ctx, v):
        xp = ctx.xp
        _, m, _ = civil_from_days(xp, _days_of(ctx, v, self.child.data_type))
        return ((m - 1) // 3 + 1).astype(np.int32)
