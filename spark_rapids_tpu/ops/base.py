"""Expression base classes.

Reference parity: GpuExpressions.scala —
- `GpuExpression.columnarEval(batch): Any` contract (:74-99) -> `Expression.eval`
- arity templates with scalar/vector dispatch and null propagation
  (GpuUnaryExpression :115-149, GpuBinaryExpression :158-199, ternary)
- GpuBoundReference / GpuBindReferences (GpuBoundAttribute.scala)
- GpuAlias / named expressions (namedExpressions.scala)
- GpuSortOrder (SortOrder used by GpuSortExec)
"""

from __future__ import annotations

import itertools
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.values import (
    ColV,
    EvalContext,
    ScalarV,
    and_validity,
    zero_nulls,
)

_expr_id_counter = itertools.count(1)


def next_expr_id() -> int:
    return next(_expr_id_counter)


def val_interval(v) -> Optional[Tuple[int, int]]:
    """Static (lo, hi) bound of an evaluated integral value, or None.
    Exact python-int arithmetic feeds the int32-narrowing proof
    (columnar.batch module docstring)."""
    if isinstance(v, ScalarV):
        if v.dtype.is_integral and not v.is_null:
            return (int(v.value), int(v.value))
        return None
    if isinstance(v, ColV) and v.dtype.is_integral:
        return v.vrange
    return None


class Expression:
    """Immutable expression-tree node."""

    def children(self) -> Tuple["Expression", ...]:
        return ()

    @property
    def data_type(self) -> DataType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children())

    @property
    def foldable(self) -> bool:
        ch = self.children()
        return bool(ch) and all(c.foldable for c in ch)

    # deterministic unless overridden (reference: nondeterministic exprs like
    # GpuRand disable certain rewrites)
    @property
    def deterministic(self) -> bool:
        return all(c.deterministic for c in self.children())

    def with_children(self, new_children: Sequence["Expression"]) -> "Expression":
        """Rebuild this node with new children (used by bind/transform)."""
        raise NotImplementedError(type(self).__name__)

    def transform_up(self, fn) -> "Expression":
        new_children = [c.transform_up(fn) for c in self.children()]
        node = self.with_children(new_children) if new_children else self
        return fn(node)

    def collect(self, pred) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children():
            out.extend(c.collect(pred))
        return out

    # -- evaluation ----------------------------------------------------------
    def eval(self, ctx: EvalContext):
        """Evaluate to a ColV or ScalarV. One implementation serves both the
        device and cpu paths via ctx.xp; expressions whose device kernel
        differs structurally (strings) override `eval_kernel` per path."""
        child_vals = [c.eval(ctx) for c in self.children()]
        return self.eval_kernel(ctx, *child_vals)

    def eval_kernel(self, ctx: EvalContext, *child_vals):
        raise NotImplementedError(type(self).__name__)

    def result_vrange(self, *child_vals) -> Optional[Tuple[int, int]]:
        """Static (lo, hi) bound of this expression's integral result given
        the child values' bounds, or None (unknown). Conservative default;
        arithmetic/conditional ops override with exact interval rules."""
        return None

    # -- identity (used for jit-cache keys and explain output) ---------------
    def fingerprint(self) -> str:
        parts = ",".join(c.fingerprint() for c in self.children())
        return f"{type(self).__name__}({self._fingerprint_extra()}{parts})"

    def _fingerprint_extra(self) -> str:
        return ""

    def sql_name(self) -> str:
        return type(self).__name__

    def __repr__(self):
        ch = ", ".join(repr(c) for c in self.children())
        return f"{type(self).__name__}({ch})"


class LeafExpression(Expression):
    def with_children(self, new_children):
        assert not new_children
        return self


class UnaryExpression(Expression):
    """Null-propagating unary template (reference: GpuUnaryExpression,
    GpuExpressions.scala:115-149)."""

    def __init__(self, child: Expression):
        self.child = child

    def children(self):
        return (self.child,)

    def with_children(self, new_children):
        return type(self)(*new_children)

    def eval_kernel(self, ctx, v):
        if isinstance(v, ScalarV):
            if v.is_null:
                return ScalarV(self.data_type, None)
            return self.eval_scalar(v)
        data = self.do_columnar(ctx, v)
        validity = v.validity
        if isinstance(data, ColV):  # string kernels return full ColV
            return ColV(data.dtype, data.data,
                        and_validity(ctx.xp, data.validity, validity),
                        data.offsets, vrange=data.vrange)
        return ColV(self.data_type, zero_nulls(ctx.xp, data, validity), validity,
                    vrange=self.result_vrange(v))

    def do_columnar(self, ctx, v: ColV):
        raise NotImplementedError(type(self).__name__)

    def eval_scalar(self, v: ScalarV) -> ScalarV:
        # fold via a 1-element numpy vector on the cpu kernel
        ctx = _scalar_fold_ctx()
        col = ColV(v.dtype, np.array([v.value], dtype=v.dtype.to_np())
                   if v.dtype is not DataType.STRING else np.array([v.value], dtype=object),
                   np.array([True]))
        out = self.do_columnar(ctx, col)
        return _fold_result(self.data_type, out)


class BinaryExpression(Expression):
    """Null-propagating binary template (reference: GpuBinaryExpression,
    GpuExpressions.scala:158-199)."""

    def __init__(self, left: Expression, right: Expression):
        self.left = left
        self.right = right

    def children(self):
        return (self.left, self.right)

    def with_children(self, new_children):
        return type(self)(*new_children)

    def eval_kernel(self, ctx, lv, rv):
        if isinstance(lv, ScalarV) and isinstance(rv, ScalarV):
            if lv.is_null or rv.is_null:
                return ScalarV(self.data_type, None)
            return self.eval_scalars(lv, rv)
        if isinstance(lv, ScalarV) and lv.is_null or \
           isinstance(rv, ScalarV) and rv.is_null:
            cap = ctx.capacity
            npdt = self.data_type.to_np()
            data = ctx.xp.zeros((cap,), dtype=npdt if npdt != object else None) \
                if self.data_type is not DataType.STRING else None
            validity = ctx.xp.zeros((cap,), dtype=bool)
            if self.data_type is DataType.STRING:
                return _null_string_col(ctx)
            return ColV(self.data_type, data, validity)
        data = self.do_columnar(ctx, lv, rv)
        validity = and_validity(
            ctx.xp,
            lv.validity if isinstance(lv, ColV) else None,
            rv.validity if isinstance(rv, ColV) else None,
        )
        if validity is None:
            validity = ctx.xp.ones((ctx.capacity,), dtype=bool)
            if ctx.is_device:
                validity = validity & ctx.row_mask()
        if isinstance(data, ColV):  # string kernels return full ColV
            return ColV(data.dtype, data.data,
                        and_validity(ctx.xp, data.validity, validity), data.offsets,
                        vrange=data.vrange)
        return ColV(self.data_type, zero_nulls(ctx.xp, data, validity), validity,
                    vrange=self.result_vrange(lv, rv))

    def do_columnar(self, ctx, lv, rv):
        """lv/rv are ColV or non-null ScalarV; kernels use `_d(v)` to get the
        broadcastable raw value."""
        raise NotImplementedError(type(self).__name__)

    def eval_scalars(self, lv: ScalarV, rv: ScalarV) -> ScalarV:
        ctx = _scalar_fold_ctx()

        def lift(s):
            if s.dtype is DataType.STRING:
                return ColV(s.dtype, np.array([s.value], dtype=object), np.array([True]))
            return ColV(s.dtype, np.array([s.value], dtype=s.dtype.to_np()),
                        np.array([True]))

        out = self.do_columnar(ctx, lift(lv), lift(rv))
        return _fold_result(self.data_type, out)


class TernaryExpression(Expression):
    def __init__(self, a: Expression, b: Expression, c: Expression):
        self.a, self.b, self.c = a, b, c

    def children(self):
        return (self.a, self.b, self.c)

    def with_children(self, new_children):
        return type(self)(*new_children)

    def eval_kernel(self, ctx, *vals):
        if all(isinstance(v, ScalarV) for v in vals) and \
           not any(v.is_null for v in vals):
            # constant fold via a 1-row cpu context
            fctx = _scalar_fold_ctx()

            def lift(s):
                if s.dtype is DataType.STRING:
                    return ColV(s.dtype, np.array([s.value], dtype=object),
                                np.array([True]))
                return ColV(s.dtype, np.array([s.value], dtype=s.dtype.to_np()),
                            np.array([True]))

            return _fold_result(self.data_type,
                                self.do_columnar(fctx, *[lift(v) for v in vals]))
        # lift string scalars to columns so string kernels see real operands
        vals = tuple(
            _lift_string_scalar(ctx, v)
            if isinstance(v, ScalarV) and not v.is_null and
            v.dtype is DataType.STRING else v
            for v in vals
        )
        if any(isinstance(v, ScalarV) and v.is_null for v in vals):
            if self.data_type is DataType.STRING:
                return _null_string_col(ctx)
            return ColV(self.data_type,
                        ctx.xp.zeros((ctx.capacity,), dtype=self.data_type.to_np()),
                        ctx.xp.zeros((ctx.capacity,), dtype=bool))
        data = self.do_columnar(ctx, *vals)
        validity = and_validity(
            ctx.xp, *[v.validity for v in vals if isinstance(v, ColV)]
        )
        if validity is None:
            validity = ctx.xp.ones((ctx.capacity,), dtype=bool)
            if ctx.is_device:
                validity = validity & ctx.row_mask()
        if isinstance(data, ColV):
            return ColV(data.dtype, data.data,
                        and_validity(ctx.xp, data.validity, validity), data.offsets,
                        vrange=data.vrange)
        return ColV(self.data_type, zero_nulls(ctx.xp, data, validity), validity,
                    vrange=self.result_vrange(*vals))

    def do_columnar(self, ctx, *vals):
        raise NotImplementedError(type(self).__name__)


def _null_string_col(ctx):
    xp = ctx.xp
    if ctx.is_device:
        return ColV(
            DataType.STRING,
            xp.zeros((8,), dtype=xp.uint8),
            xp.zeros((ctx.capacity,), dtype=bool),
            xp.zeros((ctx.capacity + 1,), dtype=xp.int32),
        )
    return ColV(DataType.STRING,
                np.full((ctx.capacity,), "", dtype=object),
                np.zeros((ctx.capacity,), dtype=bool))


def _scalar_fold_ctx() -> EvalContext:
    return EvalContext(np, False, [], 1, 1)


def _fold_result(dtype: DataType, out) -> ScalarV:
    """Convert a 1-row kernel result back to a scalar (handles kernels that
    return a full ColV, e.g. string producers and validity-computing casts)."""
    if isinstance(out, ColV):
        valid = bool(np.asarray(out.validity)[0])
        if not valid:
            return ScalarV(dtype, None)
        v = out.data[0]
        if isinstance(v, np.generic):
            v = v.item()
        return ScalarV(dtype, v)
    v = np.asarray(out)[0]
    if isinstance(v, np.generic):
        v = v.item()
    return ScalarV(dtype, v)


def _lift_string_scalar(ctx: EvalContext, s: ScalarV) -> ColV:
    """Materialize a string scalar as a real column on either path."""
    if ctx.is_device:
        from spark_rapids_tpu.columnar import strings as S
        import jax.numpy as jnp

        v = S.as_view(ctx, s)
        n = len(s.value.encode("utf-8"))
        byte_cap = max(8, ctx.capacity * max(n, 1))
        validity = v.validity & ctx.row_mask()
        data, offsets = S.build_from_plan(
            [v.data], jnp.zeros((ctx.capacity,), jnp.int32),
            jnp.zeros((ctx.capacity,), jnp.int32),
            jnp.where(validity, n, 0), byte_cap)
        return ColV(DataType.STRING, data, validity, offsets)
    return ColV(DataType.STRING,
                np.full((ctx.capacity,), s.value, dtype=object),
                np.ones((ctx.capacity,), dtype=bool))


def _d(v):
    """Raw broadcastable data of a ColV or non-null ScalarV operand."""
    if isinstance(v, ColV):
        return v.data
    return v.value


# ---------------------------------------------------------------------------
# References / named expressions
# ---------------------------------------------------------------------------
class AttributeReference(LeafExpression):
    """A named column of the input relation. Resolved to a BoundReference
    before execution (reference: GpuBoundAttribute.scala)."""

    def __init__(self, name: str, dtype: DataType, nullable: bool = True,
                 expr_id: Optional[int] = None):
        self.name = name
        self._dtype = dtype
        self._nullable = nullable
        self.expr_id = expr_id if expr_id is not None else next_expr_id()

    @property
    def data_type(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    @property
    def foldable(self):
        return False

    def eval_kernel(self, ctx):
        raise RuntimeError(
            f"unbound attribute {self.name}#{self.expr_id}; run bind_references first"
        )

    def _fingerprint_extra(self):
        return f"{self.name}#{self.expr_id}:{self._dtype.name};"

    def __repr__(self):
        return f"{self.name}#{self.expr_id}"


class BoundReference(LeafExpression):
    """Ordinal reference into the input batch (reference: GpuBoundReference)."""

    def __init__(self, ordinal: int, dtype: DataType, nullable: bool = True):
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable

    @property
    def data_type(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    @property
    def foldable(self):
        return False

    def eval(self, ctx: EvalContext):
        return ctx.columns[self.ordinal]

    def _fingerprint_extra(self):
        return f"{self.ordinal}:{self._dtype.name};"

    def __repr__(self):
        return f"input[{self.ordinal}:{self._dtype.name}]"


class Alias(UnaryExpression):
    """Named result (reference: GpuAlias, namedExpressions.scala)."""

    def __init__(self, child: Expression, name: str, expr_id: Optional[int] = None):
        super().__init__(child)
        self.name = name
        self.expr_id = expr_id if expr_id is not None else next_expr_id()

    def with_children(self, new_children):
        return Alias(new_children[0], self.name, self.expr_id)

    @property
    def data_type(self):
        return self.child.data_type

    @property
    def nullable(self):
        return self.child.nullable

    def eval_kernel(self, ctx, v):
        return v

    def to_attribute(self) -> AttributeReference:
        return AttributeReference(self.name, self.data_type, self.nullable,
                                  self.expr_id)

    def _fingerprint_extra(self):
        return f"{self.name};"

    def __repr__(self):
        return f"{self.child!r} AS {self.name}#{self.expr_id}"


def to_attribute(e: Expression) -> AttributeReference:
    if isinstance(e, AttributeReference):
        return e
    if isinstance(e, Alias):
        return e.to_attribute()
    raise TypeError(f"not a named expression: {e!r}")


class SortOrder:
    """Sort key descriptor (reference: GpuSortOrder)."""

    __slots__ = ("child", "ascending", "nulls_first")

    def __init__(self, child: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.child = child
        self.ascending = ascending
        # Spark default: NULLS FIRST for ASC, NULLS LAST for DESC
        self.nulls_first = ascending if nulls_first is None else nulls_first

    def fingerprint(self):
        return f"SortOrder({self.child.fingerprint()},{self.ascending},{self.nulls_first})"

    def __repr__(self):
        d = "ASC" if self.ascending else "DESC"
        n = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.child!r} {d} {n}"
