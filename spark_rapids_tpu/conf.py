"""Typed, self-documenting configuration registry.

Reference parity: sql-plugin RapidsConf.scala (ConfBuilder/TypedConfBuilder/
ConfEntry registry with defaults, validators, doc strings and markdown doc
generation, RapidsConf.scala:116-237; ~60 `spark.rapids.*` keys).

Keys here use the `rapids.tpu.*` prefix. Per-operator enable keys are
generated automatically by the plan-rewrite rule registry
(see spark_rapids_tpu/plan/overrides.py, reference GpuOverrides.scala:125-130).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class ConfEntry:
    """One registered configuration key (reference: ConfEntry, RapidsConf.scala:116)."""

    def __init__(
        self,
        key: str,
        converter: Callable[[str], Any],
        doc: str,
        default: Any,
        is_internal: bool = False,
        checker: Optional[Callable[[Any], Optional[str]]] = None,
    ):
        self.key = key
        self.converter = converter
        self.doc = doc
        self.default = default
        self.is_internal = is_internal
        self.checker = checker

    def get(self, settings: Dict[str, Any]) -> Any:
        if self.key in settings:
            raw = settings[self.key]
            value = self.converter(raw) if isinstance(raw, str) else raw
        else:
            value = self.default
        if self.checker is not None and value is not None:
            err = self.checker(value)
            if err:
                raise ValueError(f"invalid value for {self.key}: {err}")
        return value

    def help_string(self) -> str:
        return f"{self.key} — {self.doc} (default: {self.default})"


def _to_bool(s: str) -> bool:
    if isinstance(s, bool):
        return s
    low = s.strip().lower()
    if low in ("true", "1", "yes", "on"):
        return True
    if low in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"cannot parse boolean: {s!r}")


def _to_bytes(s: str) -> int:
    """Parse '512m', '1g', '64k', plain ints."""
    if isinstance(s, int):
        return s
    s = s.strip().lower()
    mult = 1
    for suffix, m in (("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30), ("t", 1 << 40)):
        if s.endswith(suffix):
            mult = m
            s = s[: -len(suffix)]
            break
    return int(float(s) * mult)


class _Builder:
    """Fluent builder (reference: ConfBuilder/TypedConfBuilder, RapidsConf.scala:116-237)."""

    def __init__(self, registry: "ConfRegistry", key: str):
        self._registry = registry
        self._key = key
        self._doc = ""
        self._internal = False
        self._checker: Optional[Callable[[Any], Optional[str]]] = None

    def doc(self, text: str) -> "_Builder":
        self._doc = text
        return self

    def internal(self) -> "_Builder":
        self._internal = True
        return self

    def check(self, fn: Callable[[Any], Optional[str]]) -> "_Builder":
        self._checker = fn
        return self

    def _create(self, converter, default) -> ConfEntry:
        entry = ConfEntry(
            self._key, converter, self._doc, default, self._internal, self._checker
        )
        self._registry.register(entry)
        return entry

    def boolean(self, default: bool) -> ConfEntry:
        return self._create(_to_bool, default)

    def integer(self, default: int) -> ConfEntry:
        return self._create(int, default)

    def double(self, default: float) -> ConfEntry:
        return self._create(float, default)

    def string(self, default: Optional[str]) -> ConfEntry:
        return self._create(str, default)

    def bytes(self, default: int) -> ConfEntry:
        return self._create(_to_bytes, default)


class ConfRegistry:
    def __init__(self):
        self._entries: Dict[str, ConfEntry] = {}
        self._lock = threading.Lock()

    def conf(self, key: str) -> _Builder:
        return _Builder(self, key)

    def register(self, entry: ConfEntry) -> None:
        with self._lock:
            if entry.key in self._entries:
                raise ValueError(f"duplicate conf key {entry.key}")
            self._entries[entry.key] = entry

    def register_dynamic(self, key: str, doc: str, default: Any, converter=_to_bool) -> ConfEntry:
        """Register an auto-generated per-operator enable key if absent.

        Reference: ReplacementRule.confKey, GpuOverrides.scala:125-130.
        """
        with self._lock:
            if key in self._entries:
                return self._entries[key]
            entry = ConfEntry(key, converter, doc, default)
            self._entries[key] = entry
            return entry

    def entries(self) -> List[ConfEntry]:
        return sorted(self._entries.values(), key=lambda e: e.key)

    def get(self, key: str) -> Optional[ConfEntry]:
        return self._entries.get(key)


REGISTRY = ConfRegistry()
_conf = REGISTRY.conf

# ---------------------------------------------------------------------------
# Core enables (reference: RapidsConf.scala SQL_ENABLED etc.)
# ---------------------------------------------------------------------------
SQL_ENABLED = _conf("rapids.tpu.sql.enabled").doc(
    "Enable the TPU columnar plan rewrite; when false every operator runs on "
    "the CPU oracle path."
).boolean(True)

EXPLAIN = _conf("rapids.tpu.sql.explain").doc(
    "Explain the plan rewrite: NONE, NOT_ON_TPU (only fallback reasons), or ALL."
).check(
    lambda v: None if v in ("NONE", "NOT_ON_TPU", "ALL") else "must be NONE|NOT_ON_TPU|ALL"
).string("NONE")

INCOMPATIBLE_OPS = _conf("rapids.tpu.sql.incompatibleOps.enabled").doc(
    "Enable operators that produce results that differ in corner cases from "
    "the CPU (float ordering, f64-as-f32 on TPU, timezone restrictions)."
).boolean(False)

HAS_NANS = _conf("rapids.tpu.sql.hasNans").doc(
    "Assume floating point data may contain NaNs (affects agg/join support tagging)."
).boolean(True)

TEST_ENABLED = _conf("rapids.tpu.sql.test.enabled").doc(
    "Strict test mode: assert every operator in the plan ran on the TPU "
    "(reference: spark.rapids.sql.test.enabled, GpuTransitionOverrides.scala:211-260)."
).internal().boolean(False)

TEST_ALLOWED_NON_TPU = _conf("rapids.tpu.sql.test.allowedNonTpu").doc(
    "Comma separated exec/expression class names allowed to stay on CPU in "
    "strict test mode (reference: spark.rapids.sql.test.allowedNonGpu)."
).internal().string("")

# ---------------------------------------------------------------------------
# Memory (reference: RapidsConf.scala:241-322)
# ---------------------------------------------------------------------------
MEMORY_FRACTION = _conf("rapids.tpu.memory.hbm.allocFraction").doc(
    "Fraction of usable HBM the framework budgets for columnar batches; the "
    "memory manager preemptively spills below this watermark (reference: "
    "spark.rapids.memory.gpu.allocFraction=0.9, GpuDeviceManager.scala:152-198)."
).check(lambda v: None if 0.0 < v <= 1.0 else "must be in (0,1]").double(0.8)

HBM_SIZE_OVERRIDE = _conf("rapids.tpu.memory.hbm.sizeOverride").doc(
    "Override detected HBM size in bytes (0 = autodetect via device memory stats)."
).bytes(0)

HOST_SPILL_STORAGE_SIZE = _conf("rapids.tpu.memory.host.spillStorageSize").doc(
    "Bound on the host staging tier before buffers overflow to disk "
    "(reference: spark.rapids.memory.host.spillStorageSize, RapidsHostMemoryStore)."
).bytes(1 << 30)

PINNED_POOL_SIZE = _conf("rapids.tpu.memory.pinnedPool.size").doc(
    "Size of the aligned host staging pool used for host<->HBM transfers "
    "(reference: spark.rapids.memory.pinnedPool.size, GpuDeviceManager.scala:200-206)."
).bytes(256 << 20)

SPILL_DIR = _conf("rapids.tpu.memory.spill.dir").doc(
    "Local directory for the disk spill tier (reference: RapidsDiskBlockManager)."
).string("")

MEMORY_DEBUG = _conf("rapids.tpu.memory.debug").doc(
    "Log every tracked device allocation/free (reference: spark.rapids.memory.gpu.debug)."
).boolean(False)

CONCURRENT_TPU_TASKS = _conf("rapids.tpu.concurrentTpuTasks").doc(
    "Number of tasks that may hold the per-chip admission semaphore at once "
    "(reference: spark.rapids.sql.concurrentGpuTasks=2, GpuSemaphore.scala)."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(2)

# ---------------------------------------------------------------------------
# Batch sizing (reference: RapidsConf.scala:309-322)
# ---------------------------------------------------------------------------
BATCH_SIZE_BYTES = _conf("rapids.tpu.sql.batchSizeBytes").doc(
    "Target size in bytes of coalesced columnar batches "
    "(reference: spark.rapids.sql.batchSizeBytes, GpuCoalesceBatches)."
).bytes(512 << 20)

MAX_READ_BATCH_SIZE_ROWS = _conf("rapids.tpu.sql.reader.batchSizeRows").doc(
    "Max rows per batch produced by file readers "
    "(reference: spark.rapids.sql.reader.batchSizeRows, GpuParquetScan.scala:571-605)."
).integer(1 << 20)

MAX_READ_BATCH_SIZE_BYTES = _conf("rapids.tpu.sql.reader.batchSizeBytes").doc(
    "Max bytes per batch produced by file readers."
).bytes(512 << 20)

IO_PREFETCH_BATCHES = _conf("rapids.tpu.io.prefetchBatches").doc(
    "Scan decode double-buffering depth: how many host-decoded batches a "
    "file scan stages AHEAD of the consumer on a background reader thread, "
    "so batch k+1 decodes (and its upload can issue) while batch k "
    "computes (docs/async-execution.md). 0 disables prefetch (decode "
    "inline on the consumer thread); with depth k up to (2 + k) decoded "
    "batches are live per scan task (the consumer's, the reader's "
    "in-hand one, and k queued) — the resource analyzer charges "
    "scan-leaf peak HBM accordingly."
).check(lambda v: None if 0 <= v <= 16 else "must be in [0,16]").integer(1)

# ---------------------------------------------------------------------------
# Per-format / per-feature enables (reference: RapidsConf.scala:433-469)
# ---------------------------------------------------------------------------
PARQUET_READ_ENABLED = _conf("rapids.tpu.sql.format.parquet.read.enabled").boolean(True)
PARQUET_DEVICE_DECODE = _conf(
    "rapids.tpu.sql.format.parquet.deviceDecode.enabled").doc(
    "Decode eligible parquet columns ON the device: raw dictionary/RLE "
    "chunk bytes upload and a jitted kernel expands runs + gathers the "
    "dictionary (reference decodes on the accelerator the same way, "
    "GpuParquetScan.scala:536-556). Ineligible columns/pages fall back to "
    "the host Arrow decoder per column."
).boolean(True)
PARQUET_WRITE_ENABLED = _conf("rapids.tpu.sql.format.parquet.write.enabled").boolean(True)
PARQUET_DEVICE_ENCODE = _conf(
    "rapids.tpu.sql.format.parquet.deviceEncode.enabled").doc(
    "Encode parquet ON the device (reference encodes on the accelerator, "
    "ColumnarOutputWriter.scala:62-177): non-null values compact (strings "
    "via a length-prefixing byte gather, booleans bit-pack) and validity "
    "bit-packs in jitted kernels per column; only the encoded PLAIN page "
    "payload downloads, then the host block-compresses pages "
    "(none/snappy/gzip/zstd — the mirror of the decode split). Applies "
    "to flat schemas (incl. the snappy DEFAULT write) without "
    "partitionBy; other codecs/nested types use the host Arrow writer."
).boolean(True)
CSV_READ_ENABLED = _conf("rapids.tpu.sql.format.csv.read.enabled").boolean(True)
CSV_DEVICE_PARSE = _conf(
    "rapids.tpu.sql.format.csv.deviceParse.enabled").doc(
    "Parse eligible CSV columns ON the device: the host finds field "
    "boundaries in one vectorized pass (quote-aware), raw bytes + offsets "
    "upload once, and jitted kernels fold the values — integers, floats, "
    "strings, dates, and zoned timestamps, including quoted fields and "
    "escaped \"\" quotes (unescaped in the host control plane before "
    "upload; reference parses CSV on the accelerator the same way, "
    "GpuBatchScanExec.scala:474-502). Ragged files fall back to the host "
    "Arrow parser."
).boolean(True)
CSV_DEVICE_MAX_SPLIT_BYTES = _conf(
    "rapids.tpu.sql.format.csv.deviceParse.maxSplitBytes").doc(
    "Largest CSV split the device parser will load whole into host memory "
    "(the boundary plan builds rows*cols int32 tables before value "
    "eligibility is known, so a near-2GiB split would cost several GiB of "
    "host RAM); bigger splits use the streaming host Arrow reader "
    "(reference bounds CSV reads with line-aligned chunks the same way, "
    "GpuBatchScanExec.scala:322-520)."
).bytes(256 << 20)
ORC_READ_ENABLED = _conf("rapids.tpu.sql.format.orc.read.enabled").boolean(True)
ORC_DEVICE_DECODE = _conf(
    "rapids.tpu.sql.format.orc.deviceDecode.enabled").doc(
    "Decode eligible ORC columns ON the device: the host walks the "
    "protobuf metadata and RLEv2/byte-RLE run headers (all four RLEv2 "
    "sub-encodings incl. PATCHED_BASE, widths <= 56 bits), raw stripe "
    "bytes upload once (zlib/snappy/zstd blocks host-decompressed "
    "first), and jitted kernels expand the runs — integers, strings "
    "(DIRECT_V2 + DICTIONARY_V2), floats, timestamps, and booleans — the "
    "reference decodes ORC on the accelerator the same way "
    "(GpuOrcScan.scala:284,709). LZO/LZ4 (no per-block decompressed size "
    "for Arrow's raw codec) and nested types fall back to the host Arrow "
    "reader."
).boolean(True)
ORC_WRITE_ENABLED = _conf("rapids.tpu.sql.format.orc.write.enabled").boolean(True)
ORC_DEVICE_ENCODE = _conf(
    "rapids.tpu.sql.format.orc.deviceEncode.enabled").doc(
    "Encode ORC ON the device (reference encodes on the accelerator, "
    "GpuOrcFileFormat.scala / ColumnarOutputWriter.scala:62-177): "
    "non-null values compact, zigzag-encode and bit-pack into the RLEv2 "
    "DIRECT payload (strings via a byte gather + RLEv2 LENGTH stream, "
    "floats/bools as raw/bit streams) in jitted kernels per column; only "
    "the encoded stream payload downloads, then the host block-compresses "
    "in ORC framing (none/zlib/snappy). Applies to flat schemas without "
    "partitionBy; decimal/nested types use the host Arrow writer."
).boolean(True)

ENABLE_FLOAT_AGG = _conf("rapids.tpu.sql.variableFloatAgg.enabled").doc(
    "Allow float aggregations whose result can vary with evaluation order "
    "(reference: spark.rapids.sql.variableFloatAgg.enabled)."
).boolean(True)

ENABLE_INT64_NARROWING = _conf("rapids.tpu.sql.int64.narrowing.enabled").doc(
    "Let device kernels compute logically-int64 expressions in int32 lanes "
    "when column value-range metadata proves the result is identical "
    "(ranges come from upload-time min/max and parquet footer statistics). "
    "XLA emulates int64 on TPU as 32-bit pairs at a measured ~9.8x cost "
    "(docs/tuning-guide.md 'int64 on TPU'); narrowing removes that cost "
    "for in-range data with no semantic change. SQL results, hashes, and "
    "stored batches are unaffected — this only changes in-kernel compute "
    "width where exactness is provable."
).boolean(True)

ENABLE_CAST_FLOAT_TO_STRING = _conf(
    "rapids.tpu.sql.castFloatToString.enabled").doc(
    "Enable the device float->STRING cast (reference: "
    "spark.rapids.sql.castFloatToString.enabled). Output follows this "
    "framework's shortest-round-trip convention (Java-style notation; "
    "parse-back-exact for all normal doubles and every float32 under "
    "this framework's own string->float parser and for correctly-"
    "rounded parsers; subnormal doubles "
    "may differ in the last digit), NOT Java's Ryu output — the "
    "reference marks the direction incompatible for the same reason. "
    "Needs an f64-capable backend; otherwise the cast stays on the CPU "
    "engine.").boolean(False)
ENABLE_CAST_STRING_TO_FLOAT = _conf(
    "rapids.tpu.sql.castStringToFloat.enabled").doc(
    "Enable the device STRING->float cast (reference: "
    "spark.rapids.sql.castStringToFloat.enabled). Grammar: optional "
    "sign, decimal with optional <=3-digit exponent, inf/infinity/nan "
    "(case-insensitive), <=48 chars after ASCII-whitespace trim; the "
    "17-digit mantissa fold scales through error-free pair arithmetic, "
    "so normal-range results match a correctly-rounded strtod (further "
    "digits only shift the exponent; subnormal results flush on "
    "accelerator backends). Unparseable strings are NULL (ANSI: error). "
    "Host and device produce bit-identical values. Needs an f64-capable "
    "backend.").boolean(False)
ENABLE_CAST_STRING_TO_TIMESTAMP = _conf(
    "rapids.tpu.sql.castStringToTimestamp.enabled").doc(
    "Enable the device STRING->TIMESTAMP cast (reference: "
    "spark.rapids.sql.castStringToTimestamp.enabled). Grammar: "
    "'YYYY-MM-DD' or 'YYYY-MM-DD[ T]HH:MM:SS[.f{1,6}][Z|+-HH:MM]' "
    "after trim; naive timestamps are UTC; invalid civil dates are "
    "NULL (ANSI: error). Pure integer math — exact on every "
    "backend.").boolean(False)

IMPROVED_TIME_OPS = _conf("rapids.tpu.sql.improvedTimeOps.enabled").doc(
    "Enable datetime ops whose range/overflow behavior differs slightly from CPU "
    "(reference: spark.rapids.sql.improvedTimeOps.enabled, RapidsConf.scala:342)."
).boolean(False)

HASH_OPTIMIZE_SORT = _conf("rapids.tpu.sql.hashOptimizeSort.enabled").doc(
    "Insert a sort after hash-based operators (aggregate, shuffled join) "
    "whose output feeds a file write, so rows with equal keys cluster and "
    "the written files compress/size better (reference: "
    "spark.rapids.sql.hashOptimizeSort.enabled, "
    "GpuTransitionOverrides.scala:171-204)."
).boolean(False)

REPLACE_SORT_MERGE_JOIN = _conf("rapids.tpu.sql.replaceSortMergeJoin.enabled").doc(
    "Replace sort-merge joins with TPU hash joins "
    "(reference: spark.rapids.sql.replaceSortMergeJoin.enabled, RapidsConf.scala:382)."
).boolean(True)

EXPORT_COLUMNAR_RDD = _conf("rapids.tpu.sql.exportColumnarRdd").doc(
    "Allow extracting device-resident columnar data from a plan for external ML "
    "(reference: spark.rapids.sql.exportColumnarRdd, ColumnarRdd.scala)."
).boolean(False)

# ---------------------------------------------------------------------------
# Shuffle (reference: RapidsConf.scala:520-596)
# ---------------------------------------------------------------------------
SHUFFLE_MANAGER_ENABLED = _conf("rapids.tpu.shuffle.manager.enabled").doc(
    "Enable the accelerated shuffle manager that keeps shuffle partitions "
    "device-resident and moves them over the transport "
    "(reference: spark.shuffle.manager=RapidsShuffleManager)."
).boolean(False)

SHUFFLE_TRANSPORT_CLASS = _conf("rapids.tpu.shuffle.transport.class").doc(
    "Fully qualified class of the shuffle transport (reference: "
    "spark.rapids.shuffle.transport.class; default is the in-process transport, "
    "ICI collective transport used under a multi-device mesh)."
).string("spark_rapids_tpu.parallel.transport.LocalShuffleTransport")

SHUFFLE_MODE = _conf("rapids.tpu.shuffle.mode").doc(
    "Shuffle data plane: 'inprocess' keeps pieces device-resident within the "
    "process (reference: RapidsShuffleInternalManager device store tier); "
    "'ici' lowers hash exchanges onto a jitted shard_map + lax.all_to_all "
    "over the session device mesh (the ICI collective replacement for the "
    "reference's UCX peer-to-peer transport, UCXShuffleTransport.scala:47-507)."
).check(lambda v: None if v in ("inprocess", "ici")
        else "must be inprocess|ici").string("inprocess")

ADAPTIVE_COALESCE = _conf(
    "rapids.tpu.sql.adaptive.coalescePartitions.enabled").doc(
    "After the shuffle map stage, merge small contiguous reduce buckets "
    "until each task holds ~advisoryPartitionSizeBytes (the Spark AQE "
    "CoalesceShufflePartitions role). Exchanges feeding a shuffled join "
    "never coalesce: both join inputs must keep identical grouping."
).boolean(True)
ADAPTIVE_TARGET_BYTES = _conf(
    "rapids.tpu.sql.adaptive.advisoryPartitionSizeBytes").doc(
    "Target bytes per post-shuffle task when adaptive coalescing is on "
    "(Spark's spark.sql.adaptive.advisoryPartitionSizeInBytes analog)."
).integer(16 << 20)

# ---------------------------------------------------------------------------
# Adaptive query execution (spark_rapids_tpu/aqe/,
# docs/adaptive-execution.md)
# ---------------------------------------------------------------------------
ADAPTIVE_ENABLED = _conf("rapids.tpu.sql.adaptive.enabled").doc(
    "Runtime re-optimization at shuffle-stage boundaries (the Spark AQE "
    "role the reference plugin runs under): a TpuAdaptiveExec wrapper "
    "materializes each exchange as a query stage, collects per-bucket "
    "MapOutputStats from host-known piece metadata (zero extra device "
    "syncs), and re-runs rule passes over the not-yet-executed remainder "
    "— skew-split, broadcast join demotion/promotion, and unified "
    "partition coalescing — with every rewritten remainder re-verified "
    "and re-analyzed against the MEASURED sizes (metrics: aqeReplans / "
    "skewSplits / joinDemotions / joinPromotions). Off (default): every "
    "plan decision stays frozen at plan time exactly as before."
).boolean(False)

ADAPTIVE_JOIN_STRATEGY = _conf(
    "rapids.tpu.sql.adaptive.joinStrategy.enabled").doc(
    "Under adaptive execution, rewrite join strategies from MEASURED "
    "build sizes: a shuffled hash join whose materialized build side "
    "fits autoBroadcastJoinThreshold demotes to a broadcast join (the "
    "stream side's not-yet-executed exchange is elided entirely), and a "
    "statically-planned broadcast join whose build subtree measured past "
    "the threshold (a blown plan-time estimate) promotes back to the "
    "shuffled form."
).boolean(True)

SKEW_JOIN_ENABLED = _conf("rapids.tpu.sql.adaptive.skewJoin.enabled").doc(
    "Under adaptive execution, split an oversized reduce bucket of a "
    "shuffled join's STREAM input into contiguous piece-range "
    "sub-partitions, replicating the build-side bucket opposite each — "
    "so a hot key's rows spread over several tasks instead of "
    "hot-spotting one (Spark's spark.sql.adaptive.skewJoin role). A "
    "bucket is skewed when its bytes exceed "
    "max(skewedPartitionFactor * median, skewedPartitionThresholdBytes)."
).boolean(True)

SKEW_JOIN_FACTOR = _conf(
    "rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor").doc(
    "Multiple of the median stream-bucket size beyond which a bucket "
    "counts as skewed (with skewedPartitionThresholdBytes as the "
    "absolute floor)."
).check(lambda v: None if v >= 1.0 else "must be >= 1.0").double(4.0)

SKEW_JOIN_THRESHOLD = _conf(
    "rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThresholdBytes").doc(
    "Absolute minimum bytes for a stream bucket to count as skewed "
    "(guards tiny queries where factor * median is noise)."
).bytes(64 << 20)

SKEW_JOIN_MAX_SPLITS = _conf(
    "rapids.tpu.sql.adaptive.skewJoin.maxSplitsPerPartition").doc(
    "Upper bound on sub-partitions one skewed bucket splits into; the "
    "per-slice target is max(advisoryPartitionSizeBytes, bucketBytes / "
    "maxSplitsPerPartition)."
).check(lambda v: None if v >= 2 else "must be >= 2").integer(8)

SHUFFLE_SERIALIZE = _conf("rapids.tpu.shuffle.serialize.enabled").doc(
    "Force shuffle pieces to cross the exchange as serialized host bytes "
    "(the fallback-tier serializer, reference: "
    "GpuColumnarBatchSerializer.scala:37-245). Serialized pieces register "
    "with the host spill store so shuffle data participates in spill."
).boolean(False)

SHUFFLE_MAX_BYTES_IN_FLIGHT = _conf("rapids.tpu.shuffle.maxBytesInFlight").doc(
    "Inflight-bytes throttle for shuffle fetches "
    "(reference: spark.rapids.shuffle.transport.maxReceiveInflightBytes)."
).bytes(1 << 30)

SHUFFLE_PARTITIONS = _conf("rapids.tpu.sql.shuffle.partitions").doc(
    "Default number of shuffle partitions (reference: spark.sql.shuffle.partitions)."
).integer(8)

# ---------------------------------------------------------------------------
# Engine / scheduler
# ---------------------------------------------------------------------------
TASK_THREADS = _conf("rapids.tpu.engine.taskThreads").doc(
    "Worker threads executing partition tasks (the Spark executor-slot analog)."
).integer(8)

FILTER_COMPACT_SYNC = _conf("rapids.tpu.engine.filterCompactSync").doc(
    "Whether the filter compacts with a row-count host sync. 'always' "
    "syncs per batch (shrinks capacity — best when fences are cheap); "
    "'never' keeps the compacted rows at the input capacity with a "
    "traced row count (no fence; padded lanes cost compute but the "
    "sync folds into whatever downstream fence happens anyway); 'auto' "
    "(default) goes lazy when the measured backend fence cost clears "
    "~5 ms (tunneled chips measure ~67 ms; local chips ~0.1-1 ms)."
).check(lambda v: None if v in ("auto", "always", "never")
        else "must be one of auto|always|never").string("auto")

AGG_COMPACT_SYNC = _conf("rapids.tpu.engine.aggCompactSync").doc(
    "Whether the partial-aggregate stage compacts its output with a "
    "row-count host sync before the shuffle. 'always' compacts every "
    "batch (best when host<->device syncs are cheap and map partitions "
    "are many); 'never' requests the sync-free lazy path wherever it "
    "applies — fixed-width buffer schemas whose un-compacted output fits "
    "the exchange's zero-copy piece cap; bigger batches and string "
    "min/max buffers still compact. 'auto' additionally requires the "
    "measured backend fence cost to clear a fixed ~5 ms threshold and "
    "the map partition count to stay under aggLazyMaxPartitions."
).check(lambda v: None if v in ("auto", "always", "never")
        else "must be one of auto|always|never").string("auto")

AGG_LAZY_MAX_PARTS = _conf("rapids.tpu.engine.aggLazyMaxPartitions").doc(
    "Upper bound on map partitions for the 'auto' lazy (sync-free) partial "
    "aggregate: beyond this many upstream partitions the un-compacted "
    "batches concatenated at the merge stage would dominate, so compaction "
    "is worth its sync."
).integer(32)

FUSION_ENABLED = _conf("rapids.tpu.sql.fusion.enabled").doc(
    "Compile whole pipelined stages — maximal chains of Filter/Project/"
    "Expand/LocalLimit feeding each other (and the update side of a "
    "partial hash aggregate) — into ONE XLA program per stage, so XLA "
    "fuses across operator boundaries and intermediate batches never "
    "materialize between exec nodes (the WholeStageCodegen analog; "
    "docs/fusion.md). Off = one jitted program per operator."
).boolean(True)

FUSION_MAX_OPS = _conf("rapids.tpu.sql.fusion.maxOps").doc(
    "Upper bound on operators fused into one stage program; a pathological "
    "deep chain past this splits into multiple stages (guards XLA compile "
    "time, which grows with the traced program)."
).check(lambda v: None if v >= 2 else "must be >= 2").integer(16)

# ---------------------------------------------------------------------------
# Single-program SPMD stages (plan/spmd.py, engine/spmd_exec.py,
# docs/spmd-stages.md)
# ---------------------------------------------------------------------------
SPMD_ENABLED = _conf("rapids.tpu.sql.spmd.enabled").doc(
    "Compile whole SPMD-eligible stage pipelines — a scan-fed fused "
    "Filter/Project chain, lowered INNER equi-joins (build side broadcast "
    "in-program via lax.all_gather), the partial hash aggregate, the hash "
    "exchange (lowered to an in-program lax.all_to_all over the session "
    "mesh), the final merge aggregate, and an optional trailing "
    "range-exchange+sort tail — into ONE jitted shard_map program over "
    "the device mesh: one device dispatch per stage chain regardless of "
    "partition count, the same program on 1 chip or a pod slice "
    "(docs/spmd-stages.md). Consecutive eligible stages CHAIN inside one "
    "program (spmd.chainStages.enabled). Ineligible stages, checked "
    "replays, and CPU fallbacks always take the host-loop executor, so "
    "the PR 4/PR 6 retry and re-attribution contracts hold unchanged. On "
    "by default since the r14 bench confirmed flagship parity on the CPU "
    "backend (BENCH_r14.json)."
).boolean(True)

SPMD_MESH_DEVICES = _conf("rapids.tpu.sql.spmd.meshDevices").doc(
    "Devices in the SPMD stage mesh (0 = all local devices). Tests pin it "
    "to exercise the 1-chip and pod-slice shapes of the same program on "
    "one host."
).integer(0)

SPMD_BUCKET_ROWS = _conf("rapids.tpu.sql.spmd.bucketRows").doc(
    "Row capacity of each per-target exchange bucket inside an SPMD stage "
    "program (0 = derive from the resource analyzer's partial-aggregate "
    "row interval, falling back to the stage input capacity, which is "
    "always sufficient). A manual value below the real per-target row "
    "count makes the in-program overflow probe trip and the stage degrade "
    "to the host-loop executor."
).integer(0)

SPMD_MAX_SORT_LANES = _conf("rapids.tpu.sql.spmd.maxSortLanes").doc(
    "Lane budget for absorbing a trailing global sort (range exchange + "
    "sort) into the SPMD stage program: the sort replicates the merged "
    "aggregate output to every shard via all_gather, so it is only taken "
    "when mesh_size * received_lanes stays under this bound; beyond it "
    "the whole stage falls back to the host-loop executor."
).integer(1 << 18)

SPMD_JOIN_LOWERING = _conf("rapids.tpu.sql.spmd.joinLowering.enabled").doc(
    "Lower INNER equi-joins below an SPMD stage's partial aggregate into "
    "the stage program: the build side assembles like a second stage "
    "input and an in-program lax.all_gather replicates it to every shard "
    "(the planned join exchanges are elided in-program; the host-loop "
    "fallback subtree keeps them), while the probe side streams on "
    "through the stage's in-program all_to_all hash exchange. Join "
    "output rows expand into a static capacity taken from the resource "
    "analyzer's join row interval (spmd.joinRows overrides); an "
    "in-program overflow probe degrades the stage to the host-loop "
    "executor rather than ever dropping a row."
).boolean(True)

SPMD_CHAIN_STAGES = _conf("rapids.tpu.sql.spmd.chainStages.enabled").doc(
    "Chain consecutive SPMD-eligible stages (a double group-by) inside "
    "ONE shard_map program: the post-exchange merged buckets of stage k "
    "become stage k+1's in-trace input, never re-assembled into [m, cap] "
    "slots through the host. Each chained segment still counts in "
    "spmdStages; deviceDispatches reflects the single shared program."
).boolean(True)

SPMD_MAX_JOIN_LANES = _conf("rapids.tpu.sql.spmd.maxJoinLanes").doc(
    "Lane budget for one in-program join's expanded output per shard: a "
    "join whose static expansion capacity (analyzer row interval or "
    "spmd.joinRows) would exceed this compiles into an impractically "
    "large program, so the whole stage falls back to the host-loop "
    "executor instead (mirrors spmd.maxSortLanes)."
).integer(1 << 17)

SPMD_JOIN_ROWS = _conf("rapids.tpu.sql.spmd.joinRows").doc(
    "Row capacity of an in-program join's expanded output per shard "
    "(0 = derive from the resource analyzer's join row interval, falling "
    "back to max(frontier lanes, gathered build lanes)). A manual value "
    "below the real match count makes the in-program join overflow probe "
    "trip and the stage degrade to the host-loop executor."
).integer(0)

SPMD_MEASURED_CAPACITY = _conf(
    "rapids.tpu.sql.spmd.measuredCapacity.enabled").doc(
    "Size SPMD stage capacities from AQE's MEASURED MapOutputStats "
    "instead of the resource analyzer's pessimistic interval whenever a "
    "prior stage of the same query already materialized (aqe/loop.py "
    "publishes per-query measured exchange stats; docs/spmd-stages.md). "
    "Measured sizing is backstopped by the in-program overflow probes — "
    "an undersized bucket degrades to the host loop, never drops a row."
).boolean(True)

COLUMN_PRUNING = _conf("rapids.tpu.sql.optimizer.columnPruning.enabled").doc(
    "Prune unreferenced columns from the logical plan before physical "
    "planning (the role Spark Catalyst's ColumnPruning rule plays for the "
    "reference plugin, which receives already-pruned plans): scans decode "
    "only consumed columns, exchanges and joins move only consumed "
    "columns, and narrowed build sides qualify for (runtime) broadcast."
).boolean(True)

BROADCAST_THRESHOLD = _conf("rapids.tpu.sql.autoBroadcastJoinThreshold").doc(
    "Max estimated bytes for a join side to be broadcast "
    "(reference: spark.sql.autoBroadcastJoinThreshold)."
).bytes(10 << 20)

RUNTIME_BROADCAST = _conf(
    "rapids.tpu.sql.adaptive.runtimeBroadcastJoin.enabled").doc(
    "Re-plan a shuffled hash join as a broadcast join at EXECUTE time when "
    "the materialized build side fits under autoBroadcastJoinThreshold "
    "(the role Spark AQE's runtime join-strategy switch plays for the "
    "reference plugin, exercised by TpchLikeAdaptiveSparkSuite): the "
    "planner can only statically broadcast when it can bound the build "
    "size from the logical plan; build sides behind aggregates/joins/file "
    "scans estimate unknown and would otherwise always pay two shuffles."
).boolean(True)

RANGE_SAMPLE_SIZE = _conf("rapids.tpu.sql.rangePartition.sampleSizePerPartition").doc(
    "Reservoir sample size per partition for range partitioning bounds "
    "(reference: GpuRangePartitioner.scala driver-side sampling)."
).integer(100)

# ---------------------------------------------------------------------------
# Execution-time fault tolerance (engine/retry.py, docs/fault-tolerance.md)
# ---------------------------------------------------------------------------
RETRY_OOM_RETRIES = _conf("rapids.tpu.execution.retry.oomRetries").doc(
    "Device re-dispatch attempts after a retryable OOM "
    "(XLA RESOURCE_EXHAUSTED -> TpuRetryOOM): each attempt first spills "
    "tracked device buffers via DeviceStore.synchronous_spill, then "
    "re-dispatches. Exhaustion escalates to TpuSplitAndRetryOOM — "
    "splittable operators (project/filter/fused stage) bisect the input "
    "batch and process halves (reference: the RMM retry/split-retry "
    "state machine the plugin wraps every GPU allocation in)."
).check(lambda v: None if v >= 0 else "must be >= 0").integer(2)

RETRY_TRANSIENT_RETRIES = _conf(
    "rapids.tpu.execution.retry.transientRetries").doc(
    "Re-dispatch attempts after a transient device error (XLA "
    "ABORTED/UNAVAILABLE/INTERNAL -> TpuTransientDeviceError), with "
    "exponential backoff and deterministic jitter between attempts."
).check(lambda v: None if v >= 0 else "must be >= 0").integer(3)

RETRY_MAX_SPLIT_DEPTH = _conf(
    "rapids.tpu.execution.retry.maxSplitDepth").doc(
    "Maximum bisection depth for split-and-retry: a batch OOMing after "
    "every spill+retry attempt is halved recursively at most this many "
    "times (2^depth pieces) before the operator gives up and degrades "
    "to the CPU path."
).check(lambda v: None if v >= 0 else "must be >= 0").integer(3)

CPU_FALLBACK_ENABLED = _conf(
    "rapids.tpu.execution.cpuFallback.enabled").doc(
    "When an operator exhausts its device retries, re-execute the failed "
    "unit of work through the CPU-oracle path instead of failing the "
    "query: project/filter/fused stages fall back per batch; operators "
    "with device-resident state (aggregate/join/sort/scan) fall back by "
    "re-planning the whole query on the CPU engine. Every fallback "
    "increments the cpuFallbackEvents metric."
).boolean(True)

CIRCUIT_BREAKER_ENABLED = _conf(
    "rapids.tpu.execution.circuitBreaker.enabled").doc(
    "Per-session device circuit breaker: after failureThreshold device "
    "failures (retry exhaustions / query-level fallbacks), the breaker "
    "opens and the remaining work routes straight to the CPU path — "
    "batch-level device ops bypass the device and new queries plan on "
    "the CPU engine — instead of burning retry budget against an "
    "unhealthy device."
).boolean(True)

CIRCUIT_BREAKER_THRESHOLD = _conf(
    "rapids.tpu.execution.circuitBreaker.failureThreshold").doc(
    "Device failures (retry exhaustions, not individual retries) the "
    "session tolerates before the circuit breaker opens."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(4)

CIRCUIT_BREAKER_COOLDOWN_MS = _conf(
    "rapids.tpu.execution.circuitBreaker.cooldownMs").doc(
    "Half-open recovery: once a breaker has been open this many "
    "milliseconds it admits up to probeQueries device probes — a probe "
    "succeeding closes the breaker (failure count resets), a probe "
    "failing re-opens it and restarts the cooldown. 0 = the pre-r18 "
    "behavior (an open breaker stays open until session.stop())."
).check(lambda v: None if v >= 0 else "must be >= 0").double(30000.0)

CIRCUIT_BREAKER_PROBE_QUERIES = _conf(
    "rapids.tpu.execution.circuitBreaker.probeQueries").doc(
    "Device queries admitted through a HALF-OPEN breaker per cooldown "
    "window before it re-latches open awaiting their verdict; the first "
    "probe that completes decides (success closes, failure re-opens)."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(1)

TASK_TIMEOUT_SECONDS = _conf("rapids.tpu.engine.taskTimeoutSeconds").doc(
    "Wall-clock budget for one partition task; a pooled job whose task "
    "exceeds it fails with a TaskFailedError(TaskTimeoutError) instead "
    "of wedging the query (0 = disabled; single-partition jobs run "
    "inline on the caller thread and are not covered). The wedged worker "
    "thread cannot be interrupted — it keeps its pool slot and semaphore "
    "permits until its device call returns — so the timeout error is "
    "typed as a device failure: the query re-executes on the CPU engine "
    "(which never touches the admission semaphore) and the circuit "
    "breaker counts the failure."
).check(lambda v: None if v >= 0 else "must be >= 0").double(0.0)

RETRY_BUDGET = _conf("rapids.tpu.engine.retryBudget").doc(
    "Total task retries one query may spend across all of its jobs "
    "(map stages, exchanges, reduce stages share the budget); once "
    "exhausted further failures are terminal. Guards against a flaky "
    "device turning a query into an unbounded retry storm."
).check(lambda v: None if v >= 0 else "must be >= 0").integer(64)

RETRY_BACKOFF_MS = _conf("rapids.tpu.engine.retryBackoffMs").doc(
    "Base backoff in milliseconds between retry attempts (task retries "
    "and transient-device re-dispatches): sleep = base * 2^attempt * "
    "(0.5 + jitter) where jitter is a deterministic hash of the retry "
    "identity — reproducible schedules, no thundering herd."
).check(lambda v: None if v >= 0 else "must be >= 0").double(5.0)

# ---------------------------------------------------------------------------
# Self-healing execution (engine/scheduler.py speculation +
# engine/watchdog.py, docs/fault-tolerance.md)
# ---------------------------------------------------------------------------
SPECULATION_ENABLED = _conf("rapids.tpu.engine.speculation.enabled").doc(
    "Cost-calibrated straggler speculation: a pooled partition task "
    "still running past max(minRuntimeMs, multiplier x its predicted "
    "duration) while at least `quantile` of its job's sibling tasks "
    "have finished gets ONE speculative duplicate (an idempotent "
    "re-execution from source, never shared device buffers); the first "
    "completion wins and the loser is cancelled through its task-scoped "
    "CancelToken. Metrics: speculativeTasks / speculativeWins."
).boolean(True)

SPECULATION_MIN_RUNTIME_MS = _conf(
    "rapids.tpu.engine.speculation.minRuntimeMs").doc(
    "Floor under the speculation threshold: a task is never speculated "
    "before running at least this long, whatever the cost model "
    "predicts — guards sub-millisecond tasks against duplicate storms."
).check(lambda v: None if v >= 0 else "must be >= 0").double(500.0)

SPECULATION_MULTIPLIER = _conf(
    "rapids.tpu.engine.speculation.multiplier").doc(
    "Straggler threshold as a multiple of the task's predicted p95 "
    "duration (the calibrated CostModel prediction when enough samples "
    "exist, the flat per-dispatch model otherwise; with no prediction "
    "at all the median of finished sibling durations stands in)."
).check(lambda v: None if v >= 1.0 else "must be >= 1.0").double(4.0)

SPECULATION_QUANTILE = _conf("rapids.tpu.engine.speculation.quantile").doc(
    "Fraction of a job's sibling tasks that must have FINISHED before "
    "any task of that job may be speculated (a uniformly slow job is "
    "not straggling; one laggard among finished siblings is)."
).check(lambda v: None if 0.0 <= v <= 1.0 else "must be in [0,1]"
        ).double(0.5)

WATCHDOG_ENABLED = _conf("rapids.tpu.engine.watchdog.enabled").doc(
    "Hung-dispatch watchdog: one scheduler-owned daemon thread "
    "heartbeats every in-flight retry-wrapped dispatch; a dispatch "
    "silent past its timeout is classified WEDGED (metric: "
    "watchdogKills), its cooperative wait-points are released so the "
    "attempt raises a retryable TpuDispatchWedged and re-dispatches on "
    "fresh buffers, and a dispatch still silent past 2x the timeout "
    "escalates by firing the owning query's CancelToken."
).boolean(True)

WATCHDOG_DISPATCH_TIMEOUT_MS = _conf(
    "rapids.tpu.engine.watchdog.dispatchTimeoutMs").doc(
    "Silence budget for one in-flight dispatch before the watchdog "
    "classifies it wedged. 0 = calibrated: 8x the active CostModel's "
    "predicted per-task wall when a prediction exists, else a 30s "
    "cold-start default."
).check(lambda v: None if v >= 0 else "must be >= 0").double(0.0)

WATCHDOG_POLL_MS = _conf("rapids.tpu.engine.watchdog.pollMs").doc(
    "Heartbeat cadence of the watchdog daemon's scan over in-flight "
    "dispatch registrations."
).check(lambda v: None if v >= 1 else "must be >= 1").double(50.0)

# ---------------------------------------------------------------------------
# Cooperative cancellation + deadline propagation (engine/cancel.py,
# docs/fault-tolerance.md)
# ---------------------------------------------------------------------------
ENGINE_DEADLINE_MS = _conf("rapids.tpu.engine.deadlineMs").doc(
    "Per-query wall-clock deadline in milliseconds (0 = none): a "
    "CancelToken armed with this budget rides the query's QueryContext "
    "and every engine chokepoint (task loop, retry backoff, admission "
    "wait, AQE replan loop, shuffle fetch remap, prefetch, sink "
    "download) polls it — expiry raises a terminal TpuDeadlineExceeded "
    "with no retry, no CPU fallback, and no partial rows, and the query "
    "releases everything it holds (semaphore permits, admission bytes, "
    "spill entries, prefetch threads). Overridable per call via "
    "df.collect(timeout=seconds) and per tenant via TpuServer."
).check(lambda v: None if v >= 0 else "must be >= 0").double(0.0)

DEADLINE_COST_PER_DISPATCH_MS = _conf(
    "rapids.tpu.engine.deadline.costPerDispatchMs").doc(
    "Admission-time deadline feasibility model (0 = disabled): predicted "
    "query work is estimated as the resource analyzer's predicted device "
    "dispatches (upper bound) times this per-dispatch cost; a query "
    "whose predicted work cannot fit its remaining deadline is REJECTED "
    "before execution (zero device dispatches, metric: deadlineRejects) "
    "instead of admitted to die mid-flight. Calibrate from bench "
    "history (BENCH_*.json record measured per-dispatch costs per "
    "platform; a tunneled backend measures ~66ms per fence)."
).check(lambda v: None if v >= 0 else "must be >= 0").double(0.0)

# ---------------------------------------------------------------------------
# Async issue-ahead execution (engine/async_exec.py, docs/async-execution.md)
# ---------------------------------------------------------------------------
ASYNC_DISPATCH = _conf("rapids.tpu.execution.asyncDispatch.enabled").doc(
    "Issue-ahead execution: operators hand downstream UNBLOCKED device "
    "futures and the query blocks on device values exactly once, at the "
    "result sink — so a device error may surface at the sink instead of "
    "the dispatch that issued the failing program. When that happens the "
    "session re-executes the query once in CHECKED mode (synchronous "
    "dispatch, donation off) where the originating operator's own "
    "spill/split-retry machinery owns the error, before any CPU fallback "
    "(metric: checkedReplays). Off = always run checked."
).boolean(True)

BUFFER_DONATION = _conf("rapids.tpu.execution.bufferDonation.enabled").doc(
    "Donate input buffers to consume-once device kernels (fused stages, "
    "aggregate update, sort gather) via XLA donate_argnums so the output "
    "reuses the input's HBM instead of allocating fresh — cuts peak HBM "
    "churn roughly in half on those paths. Effective only on platforms "
    "that support donation (not the CPU backend). A donated dispatch "
    "cannot re-dispatch in place after a failure (its inputs are gone), "
    "so failures escalate to the query-level checked replay, which runs "
    "with donation off (docs/async-execution.md)."
).boolean(True)

BUFFER_DONATION_ASSUME_SUPPORTED = _conf(
    "rapids.tpu.execution.bufferDonation.assumeSupported").doc(
    "Treat the current backend as donation-capable even when it is the "
    "CPU backend (tests exercise the donation key-threading and the "
    "escalation contract without a real chip)."
).internal().boolean(False)

# ---------------------------------------------------------------------------
# Fault injection (utils/faultinject.py; the chaos-test substrate)
# ---------------------------------------------------------------------------
FAULT_INJECTION_ENABLED = _conf(
    "rapids.tpu.test.faultInjection.enabled").doc(
    "Enable the deterministic fault-injection harness: registered "
    "execution sites (device dispatches, transfers, shuffle fetches) "
    "consult a seeded PRF before running and raise the site's fault "
    "kind when it fires. Results must stay identical to the CPU oracle "
    "under every injected fault pattern (tests/test_faults.py)."
).boolean(False)

FAULT_INJECTION_SEED = _conf("rapids.tpu.test.faultInjection.seed").doc(
    "Seed of the fault-injection PRF; the injection decision for "
    "(site, invocation N) is a pure function of (seed, site, N), so a "
    "run replays exactly under the same seed."
).integer(0)

FAULT_INJECTION_SITES = _conf("rapids.tpu.test.faultInjection.sites").doc(
    "Comma-separated injection sites, each 'name' or 'name:kind' with "
    "kind one of oom|dispatch|transfer|fetch|delay|wedge|device_loss "
    "('*' = every registered site at its default kind; the cancel, "
    "delay, wedge, and device_loss kinds are explicit opt-ins). "
    "Registered sites: see spark_rapids_tpu.utils.faultinject.SITES / "
    "docs/fault-tolerance.md."
).string("*")

FAULT_INJECTION_RATE = _conf("rapids.tpu.test.faultInjection.rate").doc(
    "Probability in [0,1] that an armed site injects on one invocation "
    "(each retry re-rolls with a fresh invocation count, so rates < 1 "
    "terminate; the CPU fallback backstops rate = 1)."
).check(lambda v: None if 0.0 <= v <= 1.0 else "must be in [0,1]"
        ).double(0.25)

FAULT_INJECTION_DELAY_MS = _conf(
    "rapids.tpu.test.faultInjection.delayMs").doc(
    "Straggler model: an armed site firing the `delay` kind sleeps this "
    "long (cancel-aware) before proceeding NORMALLY — the work still "
    "happens and results stay oracle-equal, the task just runs late, "
    "which is what straggler speculation exists to absorb."
).check(lambda v: None if v >= 0 else "must be >= 0").double(400.0)

FAULT_INJECTION_DEFER_TO_SINK = _conf(
    "rapids.tpu.test.faultInjection.deferToSink").doc(
    "Model async dispatch's error timing: a fault that fires at a "
    "device-compute site (scan/fused/agg/join/sort) is RECORDED instead "
    "of raised, and surfaces at the next result-sink download "
    "(transfer.download) re-attributed to its originating site — "
    "exactly how a real XLA async error reaches the host. The checked "
    "replay (asyncDispatch doc) disables deferral, so the replay's "
    "faults raise at their sites where split-retry owns them."
).internal().boolean(False)

# ---------------------------------------------------------------------------
# Static analysis (plan/verify.py, docs/static-analysis.md)
# ---------------------------------------------------------------------------
PLAN_VERIFY = _conf("rapids.tpu.sql.planVerify.enabled").doc(
    "Run the static plan verifier on every FINAL physical plan before "
    "execution: schema (name/dtype/nullability) propagates bottom-up — "
    "including through TpuFusedStage member chains — and plans with "
    "unresolvable column references, dtype drift, host/device edges "
    "missing a transition node, or fused-stage accounting mismatches "
    "are rejected before any kernel runs (the GpuOverrides static-"
    "tagging safety net extended to the post-fusion plan). Violations "
    "also render in EXPLAIN under '== Plan verification =='."
).boolean(True)

PLAN_VERIFY_FAIL = _conf("rapids.tpu.sql.planVerify.failOnViolation").doc(
    "Raise PlanVerificationError when the plan verifier finds "
    "violations (default). When false the verifier is observe-only: "
    "violations surface in EXPLAIN output but the plan still executes "
    "— the triage mode for a rejected production plan."
).boolean(True)

RESOURCE_ANALYSIS = _conf("rapids.tpu.sql.resourceAnalysis.enabled").doc(
    "Run the plan-time resource analyzer on every FINAL physical plan: a "
    "bottom-up abstract interpretation propagating row-count bounds, padded "
    "batch shape sets, and a peak-HBM watermark (including transient "
    "doubles: sort buffers, hash-join build tables, shuffle staging, "
    "partial-agg scratch) per operator — including through TpuFusedStage "
    "member chains. Emits per-stage peak-byte estimates, predicted jit "
    "shape-bucket compile keys, and predicted device dispatches; typed "
    "violations (OOM_HAZARD, SPILL_LIKELY, RECOMPILE_CHURN, "
    "UNBOUNDED_GENERATE) render in EXPLAIN under '== Resource analysis ==' "
    "and feed admission-weight hints to the TPU semaphore and headroom "
    "hints to the spill framework (docs/static-analysis.md)."
).boolean(True)

RESOURCE_ANALYSIS_FAIL = _conf(
    "rapids.tpu.sql.resourceAnalysis.failOnViolation").doc(
    "Raise ResourceAnalysisError before execution when the resource "
    "analyzer finds a fatal violation (OOM_HAZARD, RECOMPILE_CHURN, "
    "UNBOUNDED_GENERATE; SPILL_LIKELY is always advisory — the spill "
    "framework exists to absorb it). Off by default: the analyzer works "
    "from static bounds, so the default mode observes — violations are "
    "recorded in session.last_plan_violations and EXPLAIN, and admission/"
    "spill hints still flow — while admission control that REJECTS "
    "queries is an explicit opt-in."
).boolean(False)

RESOURCE_STATS_MAX_ROWS = _conf(
    "rapids.tpu.sql.resourceAnalysis.statsMaxRows").doc(
    "Largest host-resident relation (total rows) the resource analyzer "
    "scans for per-column distinct-count stats at plan time; bigger "
    "relations skip the scan and keep loose row bounds (plan-time cost "
    "guard: the stats pass is O(rows log rows) per column)."
).internal().integer(1 << 17)

RESOURCE_HBM_BUDGET = _conf(
    "rapids.tpu.sql.resourceAnalysis.hbmBudgetBytes").doc(
    "HBM byte budget the resource analyzer checks predicted peaks "
    "against. 0 (default) uses the device manager's budget (detected "
    "HBM x rapids.tpu.memory.hbm.allocFraction); a nonzero override "
    "lets admission policy be tested or tightened independently of the "
    "physical device."
).bytes(0)

PLACEMENT_ENABLED = _conf("rapids.tpu.sql.placement.enabled").doc(
    "Run the cost-based placement analyzer on every FINAL physical plan "
    "(plan/placement.py, docs/placement.md): a bottom-up abstract cost "
    "interpreter that prices each operator on the device (fitted "
    "CostModel from obs/calibrate.py) and on the host (a parallel "
    "host-side coefficient fit from CPU-fallback history and *_cpu "
    "BENCH artifacts), adds transfer-edge costs at every would-be "
    "boundary, and chooses a per-subtree placement by dynamic "
    "programming — emitting MIXED plans realized with HostToDeviceExec/"
    "DeviceToHostExec transitions. The placed plan is re-verified and "
    "re-priced (planVerify placement rules, resourceAnalysis admission "
    "cost), rendered in EXPLAIN under '== Placement ==', and every "
    "decision lands in the flight recorder with a post-hoc "
    "placementRegret signal. Off by default: placement changes which "
    "backend executes each operator."
).boolean(False)

PLACEMENT_MODE = _conf("rapids.tpu.sql.placement.mode").doc(
    "Placement strategy when the analyzer is enabled. 'auto' (default): "
    "DP over fitted device/host/transfer costs, cold-start falling back "
    "to all-device below minSamples. 'device': force every operator "
    "onto the TPU (today's behavior, useful as the A side of an A/B). "
    "'host': force the whole plan host-side — the toy-scale escape "
    "hatch and the training source for the host-side coefficient fit."
).check(
    lambda v: None if v in ("auto", "device", "host")
    else "must be auto|device|host"
).string("auto")

PLACEMENT_MIN_SAMPLES = _conf("rapids.tpu.sql.placement.minSamples").doc(
    "Minimum fitted samples an operator class needs on BOTH the device "
    "and host cost models before 'auto' placement will move it off the "
    "device. Below this the class is cold and pinned to the TPU — the "
    "cold-start contract: an unwarmed model reproduces all-device "
    "plans exactly."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(5)

# ---------------------------------------------------------------------------
# Multi-tenant serving runtime (engine/server.py, plan/plan_cache.py,
# engine/admission.py, docs/serving.md)
# ---------------------------------------------------------------------------
PLAN_CACHE_ENABLED = _conf("rapids.tpu.serving.planCache.enabled").doc(
    "Cache fully planned, verified, and analyzed physical plans keyed by "
    "a canonical plan signature (logical plan structure with normalized "
    "expression ids + leaf data identity + every explicitly-set conf "
    "key). A steady-state repeat query skips planning, verification, AND "
    "resource analysis, and — because the cached plan carries the "
    "original expression objects — its kernels hit the jit cache with "
    "zero retracing (metrics: planCacheHits / planCacheMisses). The "
    "cache is shared by every live session and cleared when the last "
    "session stops."
).boolean(True)

PLAN_CACHE_MAX_ENTRIES = _conf(
    "rapids.tpu.serving.planCache.maxEntries").doc(
    "LRU bound on cached physical plans. Entries pin their leaf data "
    "(host batches of in-memory relations) alive, so the bound also "
    "bounds that residency."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(256)

ADMISSION_ENABLED = _conf("rapids.tpu.serving.admission.enabled").doc(
    "Analyzer-driven query admission (docs/serving.md): instead of "
    "first-come-first-served semaphore entry alone, each query declares "
    "the resource analyzer's predicted peak-HBM bytes before executing; "
    "a query only starts when aggregate admitted bytes + its own fit "
    "under the HBM budget — heavy plans queue, light plans interleave "
    "past them (bounded by admission.maxBypass). Queries without a "
    "resource report (analysis disabled or the estimator failed) admit "
    "immediately; the task-level TpuSemaphore remains the inner gate."
).boolean(True)

ADMISSION_MAX_BYPASS = _conf("rapids.tpu.serving.admission.maxBypass").doc(
    "How many younger queries may be admitted past a waiting (heavy) "
    "query before it becomes the blocking head of the queue and no "
    "later arrival may admit until it does — bounds starvation under a "
    "steady stream of light queries."
).check(lambda v: None if v >= 0 else "must be >= 0").integer(8)

ADMISSION_MAX_QUEUE_DEPTH = _conf(
    "rapids.tpu.serving.admission.maxQueueDepth").doc(
    "Overload shedding, depth bound (0 = unbounded): how many queries "
    "may WAIT in analyzer-driven admission at once; an arrival past the "
    "bound is refused immediately with a terminal TpuOverloadedError "
    "(metric: shedQueries) instead of joining a queue whose wait "
    "already exceeds any useful deadline (docs/fault-tolerance.md)."
).check(lambda v: None if v >= 0 else "must be >= 0").integer(0)

ADMISSION_MAX_QUEUE_WAIT_MS = _conf(
    "rapids.tpu.serving.admission.maxQueueWaitMs").doc(
    "Overload shedding, wait bound in milliseconds (0 = unbounded): a "
    "query that has waited in admission longer than this is refused "
    "with a terminal TpuOverloadedError (metric: shedQueries) rather "
    "than admitted to die — under sustained overload, bounded tail "
    "latency comes from shedding work, not queueing it."
).check(lambda v: None if v >= 0 else "must be >= 0").double(0.0)

DRAIN_POLICY = _conf("rapids.tpu.serving.drain.policy").doc(
    "What TpuServer.drain() does with in-flight queries: 'await' lets "
    "them finish (up to drain.timeoutMs, then cancels the stragglers), "
    "'cancel' fires every in-flight query's CancelToken immediately. "
    "Either way the server stops admitting first (new queries shed with "
    "TpuOverloadedError) and tears the runtime down only once quiesced."
).check(lambda v: None if v in ("await", "cancel")
        else "must be await|cancel").string("await")

DRAIN_TIMEOUT_MS = _conf("rapids.tpu.serving.drain.timeoutMs").doc(
    "Bound on how long TpuServer.drain() (and session.stop() with "
    "queries in flight) waits for in-flight queries to quiesce before "
    "tearing down anyway; under the 'await' policy, stragglers past the "
    "bound are cancelled."
).check(lambda v: None if v >= 0 else "must be >= 0").double(10000.0)

MICRO_BATCH_WINDOW_MS = _conf(
    "rapids.tpu.serving.microBatch.windowMs").doc(
    "Cross-query micro-batching window in milliseconds (0 = off). "
    "Eligible queries (per-partition-independent Filter/Project "
    "pipelines over one in-memory relation) that share a plan SHAPE "
    "signature and arrive within the window are packed into ONE query "
    "— each constituent's partitions ride as partitions of a shared "
    "padded device program — and de-multiplexed at the sink by "
    "partition range (metrics: microBatches / microBatchedQueries). "
    "Requires submitting through a session wired to a TpuServer's "
    "micro-batcher (engine/server.py)."
).check(lambda v: None if v >= 0 else "must be >= 0").double(0.0)

MICRO_BATCH_MAX_QUERIES = _conf(
    "rapids.tpu.serving.microBatch.maxQueries").doc(
    "Largest number of queries packed into one micro-batch window; a "
    "window closes early once this many have joined."
).check(lambda v: None if v >= 2 else "must be >= 2").integer(8)

# ---------------------------------------------------------------------------
# Encoded (compressed) columnar execution (columnar/encoded.py,
# docs/compressed-execution.md)
# ---------------------------------------------------------------------------
ENCODED_ENABLED = _conf("rapids.tpu.sql.encoded.enabled").doc(
    "Keep dictionary-encoded parquet STRING columns ENCODED in HBM as "
    "int32 codes plus one shared device dictionary, and compute on the "
    "codes: equality/IN/IS NULL filters rewrite their literals into code "
    "space once per dictionary, hash aggregates group directly on codes "
    "(the dictionary is gathered only at finalize/sink), hash joins on "
    "dictionary keys align the two sides through a build-time code-remap "
    "table, and the serialized shuffle ships codes + one dictionary copy "
    "per piece instead of expanded strings. Every other consumer decodes "
    "at its operator boundary through the explicit materialize() path "
    "(metrics: encodedColumns / lateMaterializations / "
    "encodedBytesSaved)."
).boolean(True)

ENCODED_MAX_DICT_FRACTION = _conf("rapids.tpu.sql.encoded.maxDictFraction").doc(
    "Per-column opt-in heuristic for encoded scan output: a "
    "dictionary-encoded column chunk stays encoded only when its "
    "dictionary size / row count is at or below this fraction (a "
    "near-unique column gains nothing from codes and would pay the "
    "dictionary residency twice)."
).check(lambda v: None if 0.0 < v <= 1.0 else "must be in (0,1]").double(0.5)

ENCODED_FIXED_DICTIONARIES = _conf(
    "rapids.tpu.sql.encoded.fixedDictionaries.enabled").doc(
    "Admit INT64 / DATE / TIMESTAMP dictionary-encoded parquet chunks as "
    "ENCODED columns under the same maxDictFraction eligibility as "
    "strings: codes stay int32 in HBM with a shared fixed-value "
    "dictionary, group-bys run on codes, sorts / range bounds / min-max "
    "and comparison predicates run in rank space through the "
    "order-preserving sorted dictionary, and materialize() is one "
    "value-table gather. Off limits encoded emission to STRING columns "
    "(the PR 9 behavior)."
).boolean(True)

RUN_AWARE_ENABLED = _conf("rapids.tpu.sql.runAware.enabled").doc(
    "Run-granular aggregate fast path (columnar/runs.py): when every "
    "column an aggregate update's keys / inputs / collapsed filters "
    "reference carries a host RLE run table from the parquet scan "
    "(pure-RLE, no-null dictionary chunks), the update batch collapses "
    "to one row per merged run plus a __run_len column — filters "
    "evaluate one predicate per run, integral sums become value x "
    "run_length, counts become sums of run lengths — before the "
    "ordinary update kernel runs. Falls back to row space whenever any "
    "eligibility condition fails (metric: runCollapsedRows)."
).boolean(True)

RUN_AWARE_MAX_RUN_FRACTION = _conf(
    "rapids.tpu.sql.runAware.maxRunFraction").doc(
    "The run collapse engages only when merged runs / rows is at or "
    "below this fraction: the run-length factor IS the win, and a "
    "near-unique column would pay the collapse (host boundary merge + "
    "re-upload) for nothing."
).check(lambda v: None if 0.0 < v <= 1.0 else "must be in (0,1]").double(0.5)


# ---------------------------------------------------------------------------
# Observability: query tracing + engine telemetry (spark_rapids_tpu/obs/,
# docs/observability.md)
# ---------------------------------------------------------------------------
OBS_TRACING = _conf("rapids.tpu.obs.tracing.enabled").doc(
    "Record a QueryContext-scoped span tree for every query: query -> "
    "stage -> operator -> site spans (dispatch/transfer/spill/retry/"
    "replan/admission-wait) with HOST-clock timestamps only — tracing "
    "adds zero device dispatches and zero host fences (pinned by "
    "tests/test_observability.py). The finished tree lands on "
    "session.last_query_trace (Perfetto/Chrome-trace export via "
    ".to_perfetto()); EXPLAIN ANALYZE forces it on for its run. Off "
    "(default): the span API is a true no-op — no allocation, no clock "
    "reads."
).boolean(False)

OBS_TRACE_MAX_SPANS = _conf("rapids.tpu.obs.trace.maxSpans").doc(
    "Upper bound on spans recorded per query; spans past the cap are "
    "counted in the trace's dropped_spans and not retained (bounds "
    "tracer memory on pathological many-partition queries)."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(20000)

OBS_TRACE_ANNOTATIONS = _conf("rapids.tpu.obs.traceAnnotations.enabled").doc(
    "Bridge every live span into a jax.profiler.TraceAnnotation (the "
    "NvtxWithMetrics analog for XProf): a jax.profiler capture taken "
    "while tracing shows the engine's span names on the host timeline. "
    "Off by default — the annotation objects cost allocations per span "
    "and matter only under an active profiler."
).boolean(False)

OBS_HISTORY_ENABLED = _conf("rapids.tpu.obs.history.enabled").doc(
    "Flight recorder (obs/history.py, docs/observability.md): persist "
    "one JSONL record per finished query — plan signature, per-operator "
    "measured spans flattened from the trace, the resource analyzer's "
    "predicted intervals, correlated engine events (retries, spills, "
    "sheds, cancellations, AQE rewrites), and the terminal status "
    "(ok/failed/cancelled/deadline/shed). Persistence is WRITE-BEHIND: "
    "a single daemon writer appends after the sink, off the query's "
    "critical path, so the flagship deviceDispatches/fencesPerQuery are "
    "identical with history on vs off (pinned by tests). Enabling "
    "history also turns span tracing on for recorded queries — the "
    "record's per-operator rows ride the span tree."
).boolean(False)

OBS_HISTORY_PATH = _conf("rapids.tpu.obs.history.path").doc(
    "Path of the query-history JSONL store. Empty (default) resolves to "
    "srt_query_history-<pid>.jsonl under the system temp directory — "
    "point it somewhere durable to accumulate calibration history "
    "across processes. One line = one complete JSON record; a corrupt "
    "trailing line (crash mid-append) is skipped on read, never fatal."
).string("")

OBS_HISTORY_MAX_BYTES = _conf("rapids.tpu.obs.history.maxBytes").doc(
    "Retention bound of the history store: when an append would push "
    "the file past this size it is compacted in place to the NEWEST "
    "records totaling at most half the bound, then the append proceeds "
    "— the store never grows past maxBytes + one record."
).check(lambda v: None if v >= 4096 else "must be >= 4096").bytes(16 << 20)

OBS_HISTORY_QUEUE_DEPTH = _conf("rapids.tpu.obs.history.queueDepth").doc(
    "Bound on query records awaiting the write-behind history writer; "
    "records past it are DROPPED (counted in the store snapshot) rather "
    "than blocking a query's completion path."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(256)

OBS_CALIBRATION_ENABLED = _conf("rapids.tpu.obs.calibration.enabled").doc(
    "Consume the fitted per-operator-class cost model (obs/calibrate.py) "
    "where the engine prices predicted work: the resource analysis "
    "renders a predicted wall-time interval, EXPLAIN ANALYZE shows a "
    "per-operator prediction-error column, and the admission-time "
    "deadline feasibility check uses calibrated per-class costs instead "
    "of the flat rapids.tpu.engine.deadline.costPerDispatchMs — which "
    "stays the cold-start fallback for classes with fewer than "
    "calibration.minSamples samples."
).boolean(True)

OBS_CALIBRATION_MIN_SAMPLES = _conf(
    "rapids.tpu.obs.calibration.minSamples").doc(
    "Samples a cost class needs before its fitted coefficients are "
    "trusted; below it the class prices at the flat "
    "deadline.costPerDispatchMs cold-start fallback "
    "(docs/observability.md, the cold-start fallback contract)."
).check(lambda v: None if v >= 1 else "must be >= 1").integer(5)

OBS_CALIBRATION_REFIT_EVERY = _conf(
    "rapids.tpu.obs.calibration.refitEvery").doc(
    "Refit the cost model from recent history every N recorded queries "
    "(on the write-behind writer thread, never the query path); 0 "
    "disables automatic refits (obs.calibrate.fit_from_store remains "
    "the manual path)."
).check(lambda v: None if v >= 0 else "must be >= 0").integer(16)

class TpuConf:
    """Resolved view of the settings map (reference: RapidsConf class).

    Exposes each registered entry as a property-style `get(entry)` as well as
    convenience attributes for the hot keys.
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self.settings: Dict[str, Any] = dict(settings or {})

    def clone_with(self, extra: Dict[str, Any]) -> "TpuConf":
        merged = dict(self.settings)
        merged.update(extra)
        return TpuConf(merged)

    def get(self, entry: ConfEntry) -> Any:
        return entry.get(self.settings)

    def get_key(self, key: str, default: Any = None) -> Any:
        entry = REGISTRY.get(key)
        if entry is not None:
            return entry.get(self.settings)
        return self.settings.get(key, default)

    def set(self, key: str, value: Any) -> "TpuConf":
        self.settings[key] = value
        if key == ENABLE_INT64_NARROWING.key:
            self.sync_int64_narrowing()
        return self

    def sync_int64_narrowing(self) -> None:
        """Align the process-wide narrowing flag with THIS conf. The flag
        is read at kernel TRACE time (no session in scope there), so it is
        a process global; this sync runs on set() AND at every query start
        (session.execute_batches), which makes the executing session's
        conf authoritative even across clone_with copies or multiple
        sessions; the flag also salts every jit-cache key, so sessions
        with different settings select different compiled programs rather
        than flushing each other's."""
        from spark_rapids_tpu.columnar.batch import (
            int64_narrowing_enabled,
            set_int64_narrowing,
        )

        want = self.get(ENABLE_INT64_NARROWING)
        if want != int64_narrowing_enabled():
            # the flag salts every jit-cache key (engine/jit_cache._key_salt)
            # so both flavors of compiled kernels coexist; flipping selects,
            # never invalidates
            set_int64_narrowing(want)

    def is_operator_enabled(self, key: str, incompat: bool, disabled_by_default: bool) -> bool:
        """Per-operator gate logic (reference: RapidsMeta.scala:185-200)."""
        if key in self.settings:
            return _to_bool(self.settings[key])
        if disabled_by_default:
            return False
        if incompat:
            return self.get(INCOMPATIBLE_OPS)
        return True

    # -- hot-key conveniences -------------------------------------------------
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return self.get(EXPLAIN)

    @property
    def test_enabled(self) -> bool:
        return self.get(TEST_ENABLED)

    @property
    def allowed_non_tpu(self) -> List[str]:
        raw = self.get(TEST_ALLOWED_NON_TPU) or ""
        return [s.strip() for s in raw.split(",") if s.strip()]

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def concurrent_tpu_tasks(self) -> int:
        return self.get(CONCURRENT_TPU_TASKS)

    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def task_threads(self) -> int:
        return self.get(TASK_THREADS)


def generate_docs_markdown() -> str:
    """Generate configs.md (reference: RapidsConf.help / docs/configs.md)."""
    lines = [
        "# spark_rapids_tpu configuration",
        "",
        "| Key | Default | Description |",
        "|---|---|---|",
    ]
    for e in REGISTRY.entries():
        if e.is_internal:
            continue
        lines.append(f"| `{e.key}` | `{e.default}` | {e.doc} |")
    return "\n".join(lines) + "\n"
