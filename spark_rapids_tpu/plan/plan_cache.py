"""Process-wide plan-signature -> compiled-plan cache (docs/serving.md).

The serving hot path: a steady-state repeat query (same logical structure,
same data, same conf — plan/signature.py) reuses a fully planned, VERIFIED,
and resource-ANALYZED physical plan, skipping the whole plan pipeline. And
because the cached plan carries the ORIGINAL expression objects, every
kernel fingerprint matches the first run's — the jit cache returns compiled
programs with zero retracing. planCacheHits/planCacheMisses prove the
zero-planning-cost claim (tests/test_serving.py pins it).

Shared by every live session (one cache per process, like the jit cache);
cleared when the last session stops (spark_rapids_tpu/session.py teardown)
— entries hold resource reports sized against the device manager's budget,
which dies with the runtime.

Entries pin their inputs alive on purpose: CachedPlan.logical keeps the
source logical plan (and thereby the id()s baked into its cache key) from
being recycled while the entry lives — see plan/signature.py.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, List, Optional

_LOCK = threading.Lock()
_CACHE: "collections.OrderedDict[str, CachedPlan]" = collections.OrderedDict()


class CachedPlan:
    """One fully-built query plan: the final physical plan, the resource
    analyzer's report (None while analysis is disabled — the conf is part
    of the key, so hit and build always agree), the combined
    verifier+analyzer violation record, and the source logical plan."""

    __slots__ = ("physical", "report", "violations", "logical",
                 "placement")

    def __init__(self, physical: Any, report: Any,
                 violations: List, logical: Any,
                 placement: Any = None):
        self.physical = physical
        self.report = report
        self.violations = list(violations)
        self.logical = logical
        # the placement analyzer's PlacementReport (None when the pass
        # was off/no-op): a cache hit must restore the session's
        # last_placement_report exactly like a fresh plan would
        self.placement = placement


def lookup(key: str) -> Optional[CachedPlan]:
    with _LOCK:
        got = _CACHE.get(key)
        if got is not None:
            _CACHE.move_to_end(key)
        return got


def insert(key: str, entry: CachedPlan,
           max_entries: int = 256) -> CachedPlan:
    """Insert keeping the FIRST entry on a race (two queries planning the
    same signature concurrently): the winner's physical plan is the one
    in flight, so later hits share the same exec/expression objects."""
    with _LOCK:
        got = _CACHE.setdefault(key, entry)
        _CACHE.move_to_end(key)
        while len(_CACHE) > max(1, int(max_entries)):
            _CACHE.popitem(last=False)
        return got


def clear() -> None:
    with _LOCK:
        _CACHE.clear()


def stats() -> dict:
    with _LOCK:
        return {"entries": len(_CACHE)}
