"""Single-program SPMD stage compiler (plan side).

The scale-out unlock of ROADMAP open item 1: where the host-loop executor
runs a stage as O(partitions x ops) per-partition dispatches with the
exchange mediated through host-visible buffers, this pass identifies
maximal SPMD-eligible stage pipelines in the FINAL physical plan and
lowers each into ONE jitted `shard_map` program over the session device
mesh (engine/spmd_exec.py builds and runs it):

    [TpuSortExec                       <- optional absorbed global-sort tail
      [TpuShuffleExchangeExec(Range)]]
        TpuHashAggregateExec(final)    <- in-program merge + finalize
          TpuShuffleExchangeExec(Hash) <- in-program lax.all_to_all epoch
            TpuHashAggregateExec(partial) + Filter/Project chain
                                       <- in-program update side
              <stage input>            <- host batches (scan) or device
                                          batches (join output, previous
                                          SPMD stage)

Best-effort TpuCoalesceBatches nodes between the pattern members are
transparent (they are perf no-ops once the whole pipeline is one program).
Theseus (PAPERS.md) is the blueprint: the distributed plan is designed
around data movement — the exchange is a collective INSIDE the stage
program, not a host-driven boundary between task loops.

Like `TpuFusedStageExec`, the wrapper node keeps the ORIGINAL operator
subtree as its child: EXPLAIN, the plan verifier, and the resource
analyzer keep seeing the member nodes, and the host-loop executor is
always one `children[0].execute()` away — ineligible-at-runtime stages,
checked replays, and CPU fallbacks all take that path, so the PR 4/PR 6
retry and re-attribution contracts hold unchanged (docs/spmd-stages.md).

Conf: rapids.tpu.sql.spmd.enabled (default off), spmd.meshDevices,
spmd.bucketRows, spmd.maxSortLanes.
"""

from __future__ import annotations

import itertools
import logging
from typing import List, Optional, Tuple

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import (
    ExecContext,
    PartitionedBatches,
    PhysicalExec,
    TpuExec,
    count_output,
)
from spark_rapids_tpu.ops.base import AttributeReference, Expression

log = logging.getLogger(__name__)

# merge-safe reduce ops the in-program aggregate supports; everything else
# (holistic percentiles, order-dependent first/last, string min/max with
# their chunked arg-extreme machinery) keeps the host-loop executor
_SPMD_OPS = ("sum", "count", "min", "max")


class SpmdStageInfo:
    """Everything the stage program builder needs, extracted once at plan
    time. Expressions are UNBOUND (over attr references); the executor
    binds them against the pruned stage-input schema."""

    __slots__ = (
        "head", "sort", "sort_keys", "final", "exchange", "partial",
        "input_node", "host_input", "input_attrs", "needed_ordinals",
        "key_exprs", "input_exprs", "filters", "op_names", "merge_ops",
        "result_exprs", "result_key_idx", "hash_key_idx", "n_keys",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


def _skip_coalesce(node: PhysicalExec) -> PhysicalExec:
    """Walk through batch coalesces between pattern members. TargetSize
    coalesces are pure perf; a RequireSingleBatch below a sort only exists
    so the host-loop sort sees one batch per partition — inside the single
    stage program both are moot."""
    from spark_rapids_tpu.exec.transitions import TpuCoalesceBatchesExec

    while isinstance(node, TpuCoalesceBatchesExec):
        node = node.children[0]
    return node


def _string_refs(e: Expression) -> List[AttributeReference]:
    return [a for a in e.collect(
        lambda n: isinstance(n, AttributeReference))
        if a.data_type is DataType.STRING]


def match_spmd_stage(node: PhysicalExec) -> Optional[SpmdStageInfo]:
    """The SPMD stage pattern rooted at `node`, or None. See the module
    docstring for the shape; docs/spmd-stages.md for the eligibility
    rules in prose."""
    from spark_rapids_tpu.exec.aggregate import (
        FINAL,
        PARTIAL,
        TpuHashAggregateExec,
        _collapse_scan_chain,
        rewrite_result_exprs,
    )
    from spark_rapids_tpu.exec.fused import TpuFusedStageExec, exprs_fusable
    from spark_rapids_tpu.exec.sort import TpuSortExec
    from spark_rapids_tpu.shuffle.exchange import (
        HashPartitioning,
        RangePartitioning,
        TpuShuffleExchangeExec,
    )

    # -- optional global-sort tail -------------------------------------------
    sort = None
    cur = node
    if isinstance(cur, TpuSortExec):
        below = _skip_coalesce(cur.children[0])
        if not (isinstance(below, TpuShuffleExchangeExec)
                and isinstance(below.partitioning, RangePartitioning)):
            return None
        rp = below.partitioning
        if len(rp.orders) != len(cur.orders) or any(
                not (isinstance(a.child, AttributeReference)
                     and isinstance(b.child, AttributeReference)
                     and a.child.expr_id == b.child.expr_id
                     and a.ascending == b.ascending
                     and a.nulls_first == b.nulls_first)
                for a, b in zip(rp.orders, cur.orders)):
            return None  # the exchange must implement exactly this sort
        sort = cur
        cur = _skip_coalesce(below.children[0])

    # -- final aggregate ------------------------------------------------------
    if not (isinstance(cur, TpuHashAggregateExec) and cur.mode == FINAL
            and cur.grouping):
        return None
    final = cur

    # -- hash exchange --------------------------------------------------------
    ex = _skip_coalesce(final.children[0])
    if not (isinstance(ex, TpuShuffleExchangeExec)
            and isinstance(ex.partitioning, HashPartitioning)
            and ex.partitioning.exprs):
        return None
    exchange = ex

    # -- partial aggregate (possibly inside an agg-form fused stage) ---------
    pa = _skip_coalesce(exchange.children[0])
    if isinstance(pa, TpuFusedStageExec) and pa.agg_form:
        pa = pa.children[0]
    if not (isinstance(pa, TpuHashAggregateExec) and pa.mode == PARTIAL):
        return None
    partial = pa

    n_keys = len(final.grouping)
    inter = exchange.children[0].output  # partial output: keys + buffers
    if len(partial.grouping) != n_keys or \
            len(inter) != n_keys + len(final.buffer_attrs):
        return None
    # positional dtype agreement between the partial's emitted buffers and
    # the final's declared ones (the exchange passes them through verbatim)
    for a, b in zip(inter, list(final.grouping) + final.buffer_attrs):
        if a.data_type != b.data_type:
            return None
    if any(a.data_type is DataType.STRING for a in final.buffer_attrs):
        return None  # string min/max buffers stay host-loop

    # the exchange must route by (a subset of) the grouping keys so equal
    # key tuples meet on one shard
    hash_key_idx: List[int] = []
    key_ids = [a.expr_id for a in inter[:n_keys]]
    for e in exchange.partitioning.exprs:
        if not isinstance(e, AttributeReference) or e.expr_id not in key_ids:
            return None
        hash_key_idx.append(key_ids.index(e.expr_id))

    # -- update side: collapse the chain below the partial -------------------
    ops = partial._update_ops()
    op_names = [op for op, _, _ in ops]
    if any(op not in _SPMD_OPS for op in op_names):
        return None
    merge_ops = final._merge_ops()
    if any(op not in _SPMD_OPS for op, _ in merge_ops):
        return None
    raw_exprs = list(partial.key_exprs) + [e for _, e, _ in ops]
    input_node, rewritten, filters = _collapse_scan_chain(
        partial.children[0], raw_exprs)
    key_exprs = rewritten[:n_keys]
    input_exprs = rewritten[n_keys:]
    if not exprs_fusable(key_exprs + input_exprs + filters):
        return None

    # -- string discipline ----------------------------------------------------
    # string stage-input columns travel as fixed-width byte matrices, so
    # they may only be consumed as DIRECT key references (hashed/grouped
    # straight from the matrix representation, shuffle/ici.py); computed
    # expressions must not read them
    for e in key_exprs:
        if e.data_type is DataType.STRING:
            if not isinstance(e, AttributeReference):
                return None
        elif _string_refs(e):
            return None
    for e in list(input_exprs) + list(filters):
        if e.data_type is DataType.STRING or _string_refs(e):
            return None

    # -- finalize side --------------------------------------------------------
    result_exprs = rewrite_result_exprs(final.agg_exprs, final.specs)
    inter_attrs = final._inter_attrs
    grouping_ids = [a.expr_id for a in final.grouping]
    result_key_idx: List[Optional[int]] = []
    for e in result_exprs:
        if e.data_type is DataType.STRING:
            if not (isinstance(e, AttributeReference)
                    and e.expr_id in grouping_ids):
                return None
            result_key_idx.append(grouping_ids.index(e.expr_id))
        else:
            if _string_refs(e):
                return None
            result_key_idx.append(None)
    if not exprs_fusable(result_exprs):
        return None

    # -- absorbed sort keys ---------------------------------------------------
    sort_keys: Optional[List[Tuple[int, bool, bool]]] = None
    if sort is not None:
        out_ids = [a.expr_id for a in final.output]
        sort_keys = []
        for o in sort.orders:
            if not (isinstance(o.child, AttributeReference)
                    and o.child.expr_id in out_ids):
                return None
            sort_keys.append((out_ids.index(o.child.expr_id),
                              o.ascending, o.nulls_first))

    # -- stage input ----------------------------------------------------------
    from spark_rapids_tpu.exec.transitions import HostToDeviceExec

    host_input = isinstance(input_node, HostToDeviceExec)
    if not host_input and input_node.placement != "tpu":
        return None

    # prune the stage input to the columns the program actually reads
    input_attrs = list(input_node.output)
    needed_ids = set()
    for e in key_exprs + input_exprs + filters:
        for a in e.collect(lambda n: isinstance(n, AttributeReference)):
            needed_ids.add(a.expr_id)
    needed_ordinals = [i for i, a in enumerate(input_attrs)
                       if a.expr_id in needed_ids]
    pruned = [input_attrs[i] for i in needed_ordinals]
    if needed_ids - {a.expr_id for a in pruned}:
        return None  # an expression reads a column the input never emits

    return SpmdStageInfo(
        head=node, sort=sort, sort_keys=sort_keys, final=final,
        exchange=exchange, partial=partial, input_node=input_node,
        host_input=host_input, input_attrs=pruned,
        needed_ordinals=needed_ordinals, key_exprs=key_exprs,
        input_exprs=input_exprs, filters=filters, op_names=op_names,
        merge_ops=merge_ops, result_exprs=result_exprs,
        result_key_idx=result_key_idx, hash_key_idx=hash_key_idx,
        n_keys=n_keys)


class TpuSpmdStageExec(TpuExec):
    """One SPMD stage pipeline compiled to a single shard_map program over
    the mesh (engine/spmd_exec.py). children[0] is the ORIGINAL subtree —
    the host-loop executor for this stage, taken whenever the program is
    ineligible at runtime, a fault exhausts its retries, or the session is
    replaying in checked mode."""

    def __init__(self, stage_id: int, head: PhysicalExec,
                 info: SpmdStageInfo):
        super().__init__(head)
        self.stage_id = stage_id
        self.info = info
        # filled by the resource analyzer (plan/resources._spmd_stage):
        # sound upper bound on the partial-aggregate output rows, sizing
        # the per-target exchange buckets inside the program
        self.bucket_rows_hint: Optional[int] = None

    @property
    def output(self):
        return self.children[0].output

    def with_children(self, new_children):
        info = match_spmd_stage(new_children[0])
        if info is None:
            # the rebuilt subtree no longer matches the pattern — hand the
            # bare subtree back rather than wrap an unrunnable stage
            return new_children[0]
        return TpuSpmdStageExec(self.stage_id, new_children[0], info)

    def node_name(self):
        inner = ["PartialAgg", "AllToAll", "FinalAgg"]
        if self.info.sort is not None:
            inner.append("Sort")
        return f"TpuSpmdStage({self.stage_id})[{'->'.join(inner)}]"

    def execute(self, ctx: ExecContext) -> PartitionedBatches:
        from spark_rapids_tpu.engine import async_exec as AX
        from spark_rapids_tpu.engine import spmd_exec
        from spark_rapids_tpu.engine.retry import (
            TpuAsyncSinkError,
            failure_is_device_rooted,
        )

        if AX.in_checked_mode() or not ctx.conf.get(C.SPMD_ENABLED):
            # the checked replay must re-attribute errors to HOST-LOOP
            # dispatch sites (docs/async-execution.md); a conf flip between
            # plan and execute degrades the same way
            return self._host_loop(ctx)
        # the fallback runs AFTER the except blocks: the in-flight
        # exception's traceback pins execute_stage's frame — including the
        # whole assembled [m, cap] input table — and the host-loop re-run
        # happens exactly when device memory is tightest
        try:
            return spmd_exec.execute_stage(self, ctx)
        except spmd_exec.SpmdStageFallback as e:
            log.warning("SPMD stage %d ineligible at runtime (%s); "
                        "degrading to the host-loop executor",
                        self.stage_id, e)
        except Exception as e:  # noqa: BLE001 — degradation boundary
            if isinstance(e, TpuAsyncSinkError) or not \
                    failure_is_device_rooted(e):
                # sink-attributed errors belong to the session's checked
                # replay; non-device errors are real bugs — neither may be
                # absorbed by the stage fallback
                raise
            log.warning("SPMD stage %d failed on-device (%r); degrading "
                        "to the host-loop executor", self.stage_id, e)
        return self._host_loop(ctx)

    def _host_loop(self, ctx: ExecContext) -> PartitionedBatches:
        pb = self.children[0].execute(ctx)
        return PartitionedBatches(
            pb.num_partitions,
            lambda p: count_output(self.metrics, pb.iterator(p)),
            bucket_costs=pb.bucket_costs)


def lower_spmd_stages(plan: PhysicalExec, conf: C.TpuConf) -> PhysicalExec:
    """Wrap every maximal SPMD-eligible pipeline in a TpuSpmdStageExec.
    Runs LAST in the plan pipeline (after fusion), so the wrapped subtree
    is exactly what the host-loop executor would run."""
    from spark_rapids_tpu.engine import async_exec as AX

    if not conf.get(C.SPMD_ENABLED) or AX.in_checked_mode():
        return plan
    counter = itertools.count(1)

    def walk(node: PhysicalExec) -> PhysicalExec:
        info = match_spmd_stage(node)
        if info is not None:
            # recurse only at/below the stage INPUT (a nested pipeline,
            # e.g. a double group-by, becomes this stage's device input);
            # the pattern members themselves are consumed by this stage
            inp = info.input_node
            new_inp = walk(inp)
            if new_inp is not inp:
                node = node.transform_up(
                    lambda n: new_inp if n is inp else n)
                info = match_spmd_stage(node)
                if info is None:  # pragma: no cover - rebuild kept shape
                    return node
            return TpuSpmdStageExec(next(counter), node, info)
        new_children = [walk(c) for c in node.children]
        if new_children and any(
                a is not b for a, b in zip(new_children, node.children)):
            node = node.with_children(new_children)
        return node

    return walk(plan)


def count_spmd_stages(plan: PhysicalExec) -> int:
    return len(plan.collect_nodes(
        lambda n: isinstance(n, TpuSpmdStageExec)))
