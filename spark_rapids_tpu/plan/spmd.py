"""Single-program SPMD stage compiler (plan side).

The scale-out unlock of ROADMAP open item 1, extended by open item 2 into
whole-query single-program compilation: where the host-loop executor runs
a stage as O(partitions x ops) per-partition dispatches with the exchange
mediated through host-visible buffers, this pass identifies maximal
SPMD-eligible stage pipelines in the FINAL physical plan and lowers each
into ONE jitted `shard_map` program over the session device mesh
(engine/spmd_exec.py builds and runs it):

    [TpuSortExec                       <- optional absorbed global-sort tail
      [TpuShuffleExchangeExec(Range)]]
        TpuHashAggregateExec(final)    <- in-program merge + finalize
          TpuShuffleExchangeExec(Hash) <- in-program lax.all_to_all epoch
            TpuHashAggregateExec(partial) + Filter/Project chain
                                       <- in-program update side
              [inner equi-join]*       <- in-program: build side broadcast
                                          via lax.all_gather, probe rows
                                          stream on through the stage
              <stage input>            <- host batches (scan) or device
                                          batches (join output, previous
                                          SPMD stage)

Two composition axes beyond the single pipeline:

- **join lowering**: shuffled/broadcast INNER equi-joins below the partial
  aggregate lower into the stage program — the build side assembles like a
  second stage input and an in-program `lax.all_gather` replicates it to
  every shard (the planned join exchanges are elided in-program; the
  host-loop subtree keeps them). The probe side streams on through the
  existing in-program all_to_all hash exchange of the aggregate.
- **stage chaining**: when the stage input is itself an SPMD-eligible
  pipeline (a double group-by), the two stages CHAIN inside one program —
  the post-exchange merged buckets of stage k are the in-trace inputs of
  stage k+1, never re-assembled into [m, cap] slots through the host.

Best-effort TpuCoalesceBatches nodes between the pattern members are
transparent (they are perf no-ops once the whole pipeline is one program).
Theseus (PAPERS.md) is the blueprint: the distributed plan is designed
around data movement — the exchange is a collective INSIDE the stage
program, not a host-driven boundary between task loops.

Like `TpuFusedStageExec`, the wrapper node keeps the ORIGINAL operator
subtree as its child: EXPLAIN, the plan verifier, and the resource
analyzer keep seeing the member nodes, and the host-loop executor is
always one `children[0].execute()` away — ineligible-at-runtime stages,
checked replays, and CPU fallbacks all take that path, so the PR 4/PR 6
retry and re-attribution contracts hold unchanged (docs/spmd-stages.md).

Conf: rapids.tpu.sql.spmd.enabled (default ON), spmd.meshDevices,
spmd.bucketRows, spmd.maxSortLanes, spmd.joinLowering.enabled,
spmd.chainStages.enabled, spmd.joinRows, spmd.measuredCapacity.enabled.
"""

from __future__ import annotations

import itertools
import logging
from typing import List, Optional, Tuple

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import (
    ExecContext,
    PartitionedBatches,
    PhysicalExec,
    TpuExec,
    count_output,
)
from spark_rapids_tpu.ops.base import AttributeReference, Expression

log = logging.getLogger(__name__)

# merge-safe reduce ops the in-program aggregate supports; everything else
# (holistic percentiles, order-dependent first/last, string min/max with
# their chunked arg-extreme machinery) keeps the host-loop executor
_SPMD_OPS = ("sum", "count", "min", "max")

# compile-time guard: joins absorbed per stage segment
_SPMD_MAX_JOINS = 8


class SpmdJoinSpec:
    """One INNER equi-join lowered into the stage program. The build side
    is a second stage input (its own collapsed Filter/Project chain over a
    host upload or device producer), broadcast in-program via all_gather;
    the probe side is the stage's streaming frontier. Expressions are
    UNBOUND; the executor binds them against the pruned schemas."""

    __slots__ = (
        "join", "n_keys",
        # build side: collapsed chain below the build child
        "build_input_node", "build_host_input", "build_attrs",
        "build_ordinals", "build_filters", "build_keys", "build_out_exprs",
        "build_out_attrs",
        # join output frontier
        "out_attrs", "out_sources", "post_filters",
        # production for the join ABOVE this one (None for the topmost
        # join — the stage's key/input exprs consume out_attrs directly)
        "prod_exprs",
        # exchanges this lowering absorbs (shuffled-join inputs)
        "covered_exchanges",
        # filled by plan/resources._spmd_stage: sound upper bound on the
        # join's output rows — sizes the static expansion capacity
        "rows_hint",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


class SpmdStageInfo:
    """Everything the stage program builder needs for ONE pipeline
    segment, extracted once at plan time. Expressions are UNBOUND (over
    attr references); the executor binds them against the pruned stage
    input / frontier schemas."""

    __slots__ = (
        "head", "sort", "sort_keys", "final", "exchange", "partial",
        "input_node", "host_input", "input_attrs", "needed_ordinals",
        "key_exprs", "input_exprs", "filters", "op_names", "merge_ops",
        "result_exprs", "result_key_idx", "hash_key_idx", "n_keys",
        # in-program joins (execution order: joins[0] innermost) and the
        # production expressions feeding the innermost join
        "joins", "bottom_exprs", "bottom_filters",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))
        if self.joins is None:
            self.joins = ()

    @property
    def top_attrs(self) -> List[AttributeReference]:
        """Schema the update-side key/input/filter expressions bind
        against: the topmost join's output frontier, or the stage input."""
        if self.joins:
            return list(self.joins[-1].out_attrs)
        return list(self.input_attrs)

    def covered_exchanges(self) -> List[PhysicalExec]:
        """Exchange nodes this segment absorbs in-program (its hash
        exchange, the absorbed range exchange, and any shuffled-join
        exchanges) — the resource analyzer's stage-coverage accounting."""
        out = [self.exchange]
        if self.sort is not None:
            out.append(_skip_coalesce(self.sort.children[0]))
        for j in self.joins:
            out.extend(j.covered_exchanges or ())
        return out


def _skip_coalesce(node: PhysicalExec) -> PhysicalExec:
    """Walk through batch coalesces between pattern members. TargetSize
    coalesces are pure perf; a RequireSingleBatch below a sort only exists
    so the host-loop sort sees one batch per partition — inside the single
    stage program both are moot."""
    from spark_rapids_tpu.exec.transitions import TpuCoalesceBatchesExec

    while isinstance(node, TpuCoalesceBatchesExec):
        node = node.children[0]
    return node


def _string_refs(e: Expression) -> List[AttributeReference]:
    return [a for a in e.collect(
        lambda n: isinstance(n, AttributeReference))
        if a.data_type is DataType.STRING]


def _string_filters_ok(filters: List[Expression]) -> bool:
    """String references inside filter conditions are admissible when
    every use sits in an equality-class position (EqualTo / EqualNullSafe
    / In over literals, IS [NOT] NULL) — exactly the code-space
    supportedness rule of columnar/encoded.py, reused here because the
    traced stage evaluates those predicates either on int32 dictionary
    CODES (encoded inputs) or on the fixed-width byte-matrix
    representation (raw strings)."""
    from spark_rapids_tpu.columnar.encoded import unbound_supported_refs

    str_ids = {a.expr_id for f in filters for a in _string_refs(f)}
    if not str_ids:
        return True
    return unbound_supported_refs(filters, str_ids) == str_ids


def _prod_exprs_ok(exprs: List[Expression]) -> bool:
    """Matrix discipline for frontier-production expressions: a STRING
    result must be a direct column reference (it travels as a byte matrix
    / code column), and computed expressions must not read strings."""
    for e in exprs:
        if e.data_type is DataType.STRING:
            if not isinstance(e, AttributeReference):
                return False
        elif _string_refs(e):
            return False
    return True


def _collapse_through(cur: PhysicalExec, exprs: List[Expression]):
    """exec/aggregate.collapse_update_chain: _collapse_scan_chain extended
    to see through non-agg-form fused stage wrappers."""
    from spark_rapids_tpu.exec.aggregate import collapse_update_chain

    return collapse_update_chain(cur, exprs)


def _eligible_join(node: PhysicalExec) -> bool:
    from spark_rapids_tpu.exec.join import (
        TpuBroadcastHashJoinExec,
        TpuShuffledHashJoinExec,
    )
    from spark_rapids_tpu.plan.logical import JoinType

    return (isinstance(node, (TpuShuffledHashJoinExec,
                              TpuBroadcastHashJoinExec))
            and node.join_type is JoinType.INNER
            and not node.build_left)


def _unwrap_join_input(node: PhysicalExec):
    """Descend through coalesce wrappers and (for shuffled joins) the
    planned exchange feeding a join input. Returns (subtree, covered
    exchange nodes): in-program the build broadcast makes both planned
    join shuffles moot, exactly like runtime broadcast demotion — the
    host-loop subtree keeps them."""
    from spark_rapids_tpu.shuffle.exchange import (
        HashPartitioning,
        TpuShuffleExchangeExec,
    )

    covered = []
    cur = _skip_coalesce(node)
    if isinstance(cur, TpuShuffleExchangeExec) and \
            isinstance(cur.partitioning, HashPartitioning):
        covered.append(cur)
        cur = _skip_coalesce(cur.children[0])
    return cur, covered


def _match_build_side(join, needed_build_attrs) -> Optional[SpmdJoinSpec]:
    """Collapse a join's build child into (input node, key exprs, output
    exprs, filters) — the second stage input this join broadcasts. Returns
    a PARTIAL SpmdJoinSpec (build fields only) or None."""
    from spark_rapids_tpu.exec.fused import exprs_fusable
    from spark_rapids_tpu.exec.transitions import HostToDeviceExec

    build_keys_raw = join.right_keys
    build_sub, covered = _unwrap_join_input(join.children[1])
    bexprs = list(build_keys_raw) + \
        [AttributeReference(a.name, a.data_type, a.nullable, a.expr_id)
         for a in needed_build_attrs]
    binput, brew, bfilters = _collapse_through(build_sub, bexprs)
    n_jk = len(build_keys_raw)
    build_keys = brew[:n_jk]
    build_out_exprs = brew[n_jk:]
    if not exprs_fusable(build_keys + build_out_exprs + bfilters):
        return None
    for e in build_keys:
        if e.data_type is DataType.STRING and \
                not isinstance(e, AttributeReference):
            return None
        if e.data_type is not DataType.STRING and _string_refs(e):
            return None
    if not _prod_exprs_ok(build_out_exprs):
        return None
    if not _string_filters_ok(bfilters):
        return None

    host_input = isinstance(binput, HostToDeviceExec)
    if not host_input and binput.placement != "tpu":
        return None
    battrs = list(binput.output)
    needed_ids = set()
    for e in list(build_keys) + list(build_out_exprs) + list(bfilters):
        for a in e.collect(lambda n: isinstance(n, AttributeReference)):
            needed_ids.add(a.expr_id)
    bords = [i for i, a in enumerate(battrs) if a.expr_id in needed_ids]
    pruned = [battrs[i] for i in bords]
    if needed_ids - {a.expr_id for a in pruned}:
        return None
    return SpmdJoinSpec(
        join=join, n_keys=n_jk, build_input_node=binput,
        build_host_input=host_input, build_attrs=pruned,
        build_ordinals=bords, build_filters=bfilters,
        build_keys=build_keys, build_out_exprs=build_out_exprs,
        build_out_attrs=list(needed_build_attrs),
        covered_exchanges=covered)


def _match_update_pipeline(partial_child: PhysicalExec,
                           raw_exprs: List[Expression],
                           join_lowering: bool):
    """Walk the chain below the partial aggregate, absorbing eligible
    INNER equi-joins. Returns (input_node, top_exprs, top_filters, joins,
    bottom_exprs, bottom_filters) where `joins` is in EXECUTION order
    (innermost first) or None on a hard ineligibility. An ineligible join
    simply becomes the stage input (device producer) — per stage, the
    lowering is maximal-but-graceful."""
    from spark_rapids_tpu.exec.fused import exprs_fusable

    levels = []  # top-down: [join node, exprs above, filters above]
    cur, exprs = partial_child, raw_exprs
    while True:
        node, rewritten, filters = _collapse_through(cur, exprs)
        if not (join_lowering and _eligible_join(node)
                and len(levels) < _SPMD_MAX_JOINS):
            bottom = (node, rewritten, filters)
            break
        join = node
        needed_exprs = list(rewritten) + list(filters)
        post_filters = list(filters)
        if join.condition is not None:
            needed_exprs.append(join.condition)
            post_filters.append(join.condition)
        if not exprs_fusable(post_filters) or \
                not _string_filters_ok(post_filters):
            bottom = (node, rewritten, filters)
            break
        needed_ids = set()
        for e in needed_exprs:
            for a in e.collect(lambda n: isinstance(n, AttributeReference)):
                needed_ids.add(a.expr_id)
        stream_ids = {a.expr_id for a in join.children[0].output}
        build_ids = {a.expr_id for a in join.children[1].output}
        if needed_ids - (stream_ids | build_ids):
            bottom = (node, rewritten, filters)
            break
        out_attrs = [a for a in join.output if a.expr_id in needed_ids]
        stream_out = [a for a in out_attrs if a.expr_id in stream_ids]
        build_out = [a for a in out_attrs if a.expr_id not in stream_ids]
        stream_keys = join.left_keys
        if any(sk.data_type != bk.data_type
               for sk, bk in zip(stream_keys, join.right_keys)):
            bottom = (node, rewritten, filters)
            break
        jspec = _match_build_side(join, build_out)
        if jspec is None:
            bottom = (node, rewritten, filters)
            break
        sout_pos = {a.expr_id: i for i, a in enumerate(stream_out)}
        bout_pos = {a.expr_id: i for i, a in enumerate(build_out)}
        jspec.out_attrs = out_attrs
        jspec.out_sources = [
            ("s", sout_pos[a.expr_id]) if a.expr_id in stream_ids
            else ("b", bout_pos[a.expr_id]) for a in out_attrs]
        jspec.post_filters = post_filters
        levels.append([jspec, rewritten])
        stream_sub, s_covered = _unwrap_join_input(join.children[0])
        jspec.covered_exchanges = list(jspec.covered_exchanges) + s_covered
        cur = stream_sub
        exprs = list(stream_keys) + [
            AttributeReference(a.name, a.data_type, a.nullable, a.expr_id)
            for a in stream_out]

    input_node, bottom_rewritten, bottom_filters = bottom
    if not _string_filters_ok(bottom_filters):
        return None
    if not levels:
        return (input_node, bottom_rewritten, bottom_filters, (), (), ())

    # execution order: innermost join first. levels[t][1] is the expr
    # list evaluated ON join t's output frontier: the top agg exprs for
    # t == 0, or the production (stream keys + pass-throughs) for the
    # join ABOVE (t - 1) otherwise.
    joins_exec = [levels[t][0] for t in range(len(levels) - 1, -1, -1)]
    for k, jspec in enumerate(joins_exec):
        t = len(levels) - 1 - k  # top-down index of this join
        if t == 0:
            jspec.prod_exprs = None  # top agg exprs consume directly
        else:
            jspec.prod_exprs = list(levels[t][1])
            if not exprs_fusable(jspec.prod_exprs) or \
                    not _prod_exprs_ok(jspec.prod_exprs):
                return None
    top_exprs = levels[0][1]
    # bottom production (feeds the innermost join): the last descend's
    # collapsed expressions over the stage input
    if not exprs_fusable(list(bottom_rewritten)) or \
            not _prod_exprs_ok(list(bottom_rewritten)):
        return None
    return (input_node, top_exprs, [], tuple(joins_exec),
            tuple(bottom_rewritten), tuple(bottom_filters))


def match_spmd_stage(node: PhysicalExec,
                     join_lowering: bool = True) -> Optional[SpmdStageInfo]:
    """The SPMD stage pattern rooted at `node`, or None. See the module
    docstring for the shape; docs/spmd-stages.md for the eligibility
    rules in prose."""
    from spark_rapids_tpu.exec.aggregate import (
        FINAL,
        PARTIAL,
        TpuHashAggregateExec,
        rewrite_result_exprs,
    )
    from spark_rapids_tpu.exec.fused import TpuFusedStageExec, exprs_fusable
    from spark_rapids_tpu.exec.sort import TpuSortExec
    from spark_rapids_tpu.shuffle.exchange import (
        HashPartitioning,
        RangePartitioning,
        TpuShuffleExchangeExec,
    )

    # -- optional global-sort tail -------------------------------------------
    sort = None
    cur = node
    if isinstance(cur, TpuSortExec):
        below = _skip_coalesce(cur.children[0])
        if not (isinstance(below, TpuShuffleExchangeExec)
                and isinstance(below.partitioning, RangePartitioning)):
            return None
        rp = below.partitioning
        if len(rp.orders) != len(cur.orders) or any(
                not (isinstance(a.child, AttributeReference)
                     and isinstance(b.child, AttributeReference)
                     and a.child.expr_id == b.child.expr_id
                     and a.ascending == b.ascending
                     and a.nulls_first == b.nulls_first)
                for a, b in zip(rp.orders, cur.orders)):
            return None  # the exchange must implement exactly this sort
        sort = cur
        cur = _skip_coalesce(below.children[0])

    # -- final aggregate ------------------------------------------------------
    if not (isinstance(cur, TpuHashAggregateExec) and cur.mode == FINAL
            and cur.grouping):
        return None
    final = cur

    # -- hash exchange --------------------------------------------------------
    ex = _skip_coalesce(final.children[0])
    if not (isinstance(ex, TpuShuffleExchangeExec)
            and isinstance(ex.partitioning, HashPartitioning)
            and ex.partitioning.exprs):
        return None
    exchange = ex

    # -- partial aggregate (possibly inside an agg-form fused stage) ---------
    pa = _skip_coalesce(exchange.children[0])
    if isinstance(pa, TpuFusedStageExec) and pa.agg_form:
        pa = pa.children[0]
    if not (isinstance(pa, TpuHashAggregateExec) and pa.mode == PARTIAL):
        return None
    partial = pa

    n_keys = len(final.grouping)
    inter = exchange.children[0].output  # partial output: keys + buffers
    if len(partial.grouping) != n_keys or \
            len(inter) != n_keys + len(final.buffer_attrs):
        return None
    # positional dtype agreement between the partial's emitted buffers and
    # the final's declared ones (the exchange passes them through verbatim)
    for a, b in zip(inter, list(final.grouping) + final.buffer_attrs):
        if a.data_type != b.data_type:
            return None
    if any(a.data_type is DataType.STRING for a in final.buffer_attrs):
        return None  # string min/max buffers stay host-loop

    # the exchange must route by (a subset of) the grouping keys so equal
    # key tuples meet on one shard
    hash_key_idx: List[int] = []
    key_ids = [a.expr_id for a in inter[:n_keys]]
    for e in exchange.partitioning.exprs:
        if not isinstance(e, AttributeReference) or e.expr_id not in key_ids:
            return None
        hash_key_idx.append(key_ids.index(e.expr_id))

    # -- update side: collapse the chain (and joins) below the partial -------
    ops = partial._update_ops()
    op_names = [op for op, _, _ in ops]
    if any(op not in _SPMD_OPS for op in op_names):
        return None
    merge_ops = final._merge_ops()
    if any(op not in _SPMD_OPS for op, _ in merge_ops):
        return None
    raw_exprs = list(partial.key_exprs) + [e for _, e, _ in ops]
    matched = _match_update_pipeline(partial.children[0], raw_exprs,
                                     join_lowering)
    if matched is None:
        return None
    (input_node, rewritten, filters, joins, bottom_exprs,
     bottom_filters) = matched
    key_exprs = rewritten[:n_keys]
    input_exprs = rewritten[n_keys:]
    if not exprs_fusable(list(key_exprs) + list(input_exprs)
                         + list(filters)):
        return None
    if not _string_filters_ok(list(filters)):
        return None

    # -- string discipline ----------------------------------------------------
    # string stage-input columns travel as fixed-width byte matrices (or
    # int32 dictionary codes when the input arrives encoded), so they may
    # only be consumed as DIRECT key references (hashed/grouped straight
    # from that representation, shuffle/ici.py); computed expressions must
    # not read them. Filter predicates over strings follow the code-space
    # supportedness rule (checked in _match_update_pipeline).
    for e in key_exprs:
        if e.data_type is DataType.STRING:
            if not isinstance(e, AttributeReference):
                return None
        elif _string_refs(e):
            return None
    for e in list(input_exprs):
        if e.data_type is DataType.STRING or _string_refs(e):
            return None

    # -- finalize side --------------------------------------------------------
    result_exprs = rewrite_result_exprs(final.agg_exprs, final.specs)
    grouping_ids = [a.expr_id for a in final.grouping]
    result_key_idx: List[Optional[int]] = []
    for e in result_exprs:
        if e.data_type is DataType.STRING:
            if not (isinstance(e, AttributeReference)
                    and e.expr_id in grouping_ids):
                return None
            result_key_idx.append(grouping_ids.index(e.expr_id))
        else:
            if _string_refs(e):
                return None
            result_key_idx.append(None)
    if not exprs_fusable(result_exprs):
        return None

    # -- absorbed sort keys ---------------------------------------------------
    sort_keys: Optional[List[Tuple[int, bool, bool]]] = None
    if sort is not None:
        out_ids = [a.expr_id for a in final.output]
        sort_keys = []
        for o in sort.orders:
            if not (isinstance(o.child, AttributeReference)
                    and o.child.expr_id in out_ids):
                return None
            sort_keys.append((out_ids.index(o.child.expr_id),
                              o.ascending, o.nulls_first))

    # -- stage input ----------------------------------------------------------
    from spark_rapids_tpu.exec.transitions import HostToDeviceExec

    host_input = isinstance(input_node, HostToDeviceExec)
    if not host_input and input_node.placement != "tpu":
        return None

    # prune the stage input to the columns the program actually reads
    consumed = (list(bottom_exprs) + list(bottom_filters)) if joins else \
        (list(key_exprs) + list(input_exprs) + list(filters))
    input_attrs = list(input_node.output)
    needed_ids = set()
    for e in consumed:
        for a in e.collect(lambda n: isinstance(n, AttributeReference)):
            needed_ids.add(a.expr_id)
    needed_ordinals = [i for i, a in enumerate(input_attrs)
                       if a.expr_id in needed_ids]
    pruned = [input_attrs[i] for i in needed_ordinals]
    if needed_ids - {a.expr_id for a in pruned}:
        return None  # an expression reads a column the input never emits

    return SpmdStageInfo(
        head=node, sort=sort, sort_keys=sort_keys, final=final,
        exchange=exchange, partial=partial, input_node=input_node,
        host_input=host_input, input_attrs=pruned,
        needed_ordinals=needed_ordinals, key_exprs=list(key_exprs),
        input_exprs=list(input_exprs), filters=list(filters),
        op_names=op_names, merge_ops=merge_ops, result_exprs=result_exprs,
        result_key_idx=result_key_idx, hash_key_idx=hash_key_idx,
        n_keys=n_keys, joins=joins, bottom_exprs=list(bottom_exprs),
        bottom_filters=list(bottom_filters))


def match_spmd_chain(node: PhysicalExec, join_lowering: bool = True,
                     chaining: bool = True
                     ) -> Optional[List[SpmdStageInfo]]:
    """A CHAIN of SPMD stage segments rooted at `node`: the outermost
    pipeline, plus every nested pipeline reachable through the stage
    input (a double group-by), innermost FIRST. Chained segments execute
    inside ONE shard_map program — the post-exchange merged buckets of
    segment k are segment k+1's in-trace input, with no [m, cap] host
    re-assembly between them. Only sortless segments chain below another
    (a mid-pipeline sort has no in-trace consumer shape)."""
    info = match_spmd_stage(node, join_lowering=join_lowering)
    if info is None:
        return None
    infos = [info]
    while chaining:
        inner = match_spmd_stage(infos[0].input_node,
                                 join_lowering=join_lowering)
        if inner is None or inner.sort is not None:
            break
        infos.insert(0, inner)
    return infos


class TpuSpmdStageExec(TpuExec):
    """One SPMD stage pipeline — possibly a CHAIN of segments — compiled
    to a single shard_map program over the mesh (engine/spmd_exec.py).
    children[0] is the ORIGINAL subtree — the host-loop executor for this
    stage, taken whenever the program is ineligible at runtime, a fault
    exhausts its retries, or the session is replaying in checked mode."""

    def __init__(self, stage_id: int, head: PhysicalExec,
                 infos: List[SpmdStageInfo], join_lowering: bool = True,
                 chaining: bool = True):
        super().__init__(head)
        self.stage_id = stage_id
        self.infos = list(infos)
        # the conf the stage was LOWERED under: a with_children rebuild
        # (an AQE stage replacement below the input) must re-match with
        # the same flags, not the defaults
        self._join_lowering = join_lowering
        self._chaining = chaining
        # filled by the resource analyzer (plan/resources._spmd_stage):
        # per segment, a sound upper bound on the partial-aggregate output
        # rows, sizing the per-target exchange buckets inside the program
        self.bucket_rows_hints: List[Optional[int]] = [None] * len(infos)

    # -- single-segment compatibility ----------------------------------------
    @property
    def info(self) -> SpmdStageInfo:
        """The OUTERMOST segment (the one whose head is children[0])."""
        return self.infos[-1]

    @property
    def bucket_rows_hint(self) -> Optional[int]:
        return self.bucket_rows_hints[-1]

    @bucket_rows_hint.setter
    def bucket_rows_hint(self, v) -> None:
        self.bucket_rows_hints[-1] = v

    @property
    def output(self):
        return self.children[0].output

    def with_children(self, new_children):
        infos = match_spmd_chain(new_children[0],
                                 join_lowering=self._join_lowering,
                                 chaining=self._chaining)
        if infos is None:
            # the rebuilt subtree no longer matches the pattern — hand the
            # bare subtree back rather than wrap an unrunnable stage
            return new_children[0]
        node = TpuSpmdStageExec(self.stage_id, new_children[0], infos,
                                join_lowering=self._join_lowering,
                                chaining=self._chaining)
        if len(infos) == len(self.infos):
            # keep the analyzer's capacity hints across the rebuild (they
            # are advisory — the overflow probes backstop a stale one)
            node.bucket_rows_hints = list(self.bucket_rows_hints)
        return node

    def node_name(self):
        segs = []
        for info in self.infos:
            inner = []
            if info.joins:
                inner.append(f"Join*{len(info.joins)}")
            inner.extend(["PartialAgg", "AllToAll", "FinalAgg"])
            if info.sort is not None:
                inner.append("Sort")
            segs.append("->".join(inner))
        return f"TpuSpmdStage({self.stage_id})[{'=>'.join(segs)}]"

    def execute(self, ctx: ExecContext) -> PartitionedBatches:
        from spark_rapids_tpu.engine import async_exec as AX
        from spark_rapids_tpu.engine import spmd_exec
        from spark_rapids_tpu.engine.retry import (
            TpuAsyncSinkError,
            failure_is_device_rooted,
        )

        if AX.in_checked_mode() or not ctx.conf.get(C.SPMD_ENABLED):
            # the checked replay must re-attribute errors to HOST-LOOP
            # dispatch sites (docs/async-execution.md); a conf flip between
            # plan and execute degrades the same way
            return self._host_loop(ctx)
        # the fallback runs AFTER the except blocks, and execute_stage
        # explicitly drops its assembled [m, cap] stage-input arrays
        # before raising a fallback: the host-loop re-run happens exactly
        # when device memory is tightest, so nothing from the abandoned
        # program may stay referenced from the in-flight exception's
        # traceback frames
        try:
            return spmd_exec.execute_stage(self, ctx)
        except spmd_exec.SpmdStageFallback as e:
            log.warning("SPMD stage %d ineligible at runtime (%s); "
                        "degrading to the host-loop executor",
                        self.stage_id, e)
        except Exception as e:  # noqa: BLE001 — degradation boundary
            if isinstance(e, TpuAsyncSinkError) or not \
                    failure_is_device_rooted(e):
                # sink-attributed errors belong to the session's checked
                # replay; non-device errors are real bugs — neither may be
                # absorbed by the stage fallback
                raise
            log.warning("SPMD stage %d failed on-device (%r); degrading "
                        "to the host-loop executor", self.stage_id, e)
        return self._host_loop(ctx)

    def _host_loop(self, ctx: ExecContext) -> PartitionedBatches:
        pb = self.children[0].execute(ctx)
        return PartitionedBatches(
            pb.num_partitions,
            lambda p: count_output(self.metrics, pb.iterator(p)),
            bucket_costs=pb.bucket_costs)


def lower_spmd_stages(plan: PhysicalExec, conf: C.TpuConf) -> PhysicalExec:
    """Wrap every maximal SPMD-eligible pipeline (chains included) in a
    TpuSpmdStageExec. Runs LAST in the plan pipeline (after fusion), so
    the wrapped subtree is exactly what the host-loop executor would
    run."""
    from spark_rapids_tpu.engine import async_exec as AX

    if not conf.get(C.SPMD_ENABLED) or AX.in_checked_mode():
        return plan
    join_lowering = bool(conf.get(C.SPMD_JOIN_LOWERING))
    chaining = bool(conf.get(C.SPMD_CHAIN_STAGES))
    counter = itertools.count(1)

    def walk(node: PhysicalExec) -> PhysicalExec:
        infos = match_spmd_chain(node, join_lowering=join_lowering,
                                 chaining=chaining)
        if infos is not None:
            # recurse only at/below the CHAIN's innermost stage input (a
            # deeper ineligible producer may still contain eligible
            # pipelines); the pattern members themselves — and every
            # chained segment — are consumed by this one program
            inp = infos[0].input_node
            new_inp = walk(inp)
            if new_inp is not inp:
                node = node.transform_up(
                    lambda n: new_inp if n is inp else n)
                infos = match_spmd_chain(node, join_lowering=join_lowering,
                                         chaining=chaining)
                if infos is None:  # pragma: no cover - rebuild kept shape
                    return node
            return TpuSpmdStageExec(next(counter), node, infos,
                                    join_lowering=join_lowering,
                                    chaining=chaining)
        new_children = [walk(c) for c in node.children]
        if new_children and any(
                a is not b for a, b in zip(new_children, node.children)):
            node = node.with_children(new_children)
        return node

    return walk(plan)


def count_spmd_stages(plan: PhysicalExec) -> int:
    """Total SPMD segments in the plan (a chained program counts each of
    its pipeline segments — the dispatch count, not this, reflects that
    they share one program)."""
    return sum(len(n.infos) for n in plan.collect_nodes(
        lambda n: isinstance(n, TpuSpmdStageExec)))
