"""User-facing function library (the pyspark.sql.functions analog).

Covers the expression surface the reference accelerates
(GpuOverrides.scala:461-1487 registry; per-category files under
org/apache/spark/sql/rapids/).
"""

from __future__ import annotations

from typing import Any, Optional, Union

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops import aggregates as A
from spark_rapids_tpu.ops import arithmetic as AR
from spark_rapids_tpu.ops import bitwise as B
from spark_rapids_tpu.ops import datetimeops as DT
from spark_rapids_tpu.ops import mathx as MX
from spark_rapids_tpu.ops import misc as MISC
from spark_rapids_tpu.ops import nulls as N
from spark_rapids_tpu.ops import stringops as S
from spark_rapids_tpu.ops.base import Alias, AttributeReference, Expression
from spark_rapids_tpu.ops.cast import Cast
from spark_rapids_tpu.ops.conditional import CaseWhen, If
from spark_rapids_tpu.ops.literals import Literal
from spark_rapids_tpu.plan.column import Column, _to_expr

ColumnOrName = Union[Column, str]


def col(name: str) -> Column:
    """An unresolved named column; resolved against the DataFrame schema at
    plan-build time (plan/dataframe.py)."""
    return Column(_UnresolvedAttribute(name))


class _UnresolvedAttribute(Expression):
    """Placeholder resolved by DataFrame methods; never evaluated."""

    def __init__(self, name: str):
        self.name = name

    def children(self):
        return ()

    def with_children(self, new_children):
        return self

    @property
    def data_type(self):
        raise RuntimeError(f"unresolved column {self.name!r}")

    def eval(self, ctx):
        raise RuntimeError(f"unresolved column {self.name!r}")

    def _fingerprint_extra(self):
        return f"{self.name};"

    def __repr__(self):
        return f"'{self.name}"


def lit(v: Any) -> Column:
    return Column(Literal(v))


def _c(e: ColumnOrName) -> Expression:
    if isinstance(e, str):
        return _UnresolvedAttribute(e)
    return _to_expr(e)


# -- conditional -------------------------------------------------------------
def when(cond: Column, value) -> "CaseBuilder":
    return CaseBuilder([(cond.expr, _to_expr(value))])


class CaseBuilder:
    def __init__(self, branches):
        self._branches = branches

    def when(self, cond: Column, value) -> "CaseBuilder":
        return CaseBuilder(self._branches + [(cond.expr, _to_expr(value))])

    def otherwise(self, value) -> Column:
        return Column(CaseWhen(self._branches, _to_expr(value)))

    @property
    def expr(self):
        return CaseWhen(self._branches, None)


def expr_if(cond: Column, a, b) -> Column:
    return Column(If(cond.expr, _to_expr(a), _to_expr(b)))


# -- null handling -----------------------------------------------------------
def coalesce(*cols: ColumnOrName) -> Column:
    return Column(N.Coalesce(*[_c(c) for c in cols]))


def isnull(c: ColumnOrName) -> Column:
    return Column(N.IsNull(_c(c)))


def isnan(c: ColumnOrName) -> Column:
    return Column(N.IsNan(_c(c)))


def nanvl(a: ColumnOrName, b: ColumnOrName) -> Column:
    return Column(N.NaNvl(_c(a), _c(b)))


# -- math --------------------------------------------------------------------
def _unary(klass):
    def fn(c: ColumnOrName) -> Column:
        return Column(klass(_c(c)))
    fn.__name__ = klass.__name__.lower()
    return fn


sqrt = _unary(MX.Sqrt)
exp = _unary(MX.Exp)
expm1 = _unary(MX.Expm1)
log = _unary(MX.Log)
log1p = _unary(MX.Log1p)
log2 = _unary(MX.Log2)
log10 = _unary(MX.Log10)
cbrt = _unary(MX.Cbrt)
sin = _unary(MX.Sin)
cos = _unary(MX.Cos)
tan = _unary(MX.Tan)
asin = _unary(MX.Asin)
acos = _unary(MX.Acos)
atan = _unary(MX.Atan)
sinh = _unary(MX.Sinh)
cosh = _unary(MX.Cosh)
tanh = _unary(MX.Tanh)
asinh = _unary(MX.Asinh)
acosh = _unary(MX.Acosh)
atanh = _unary(MX.Atanh)
cot = _unary(MX.Cot)
rint = _unary(MX.Rint)
floor = _unary(MX.Floor)
ceil = _unary(MX.Ceil)
degrees = _unary(MX.ToDegrees)
radians = _unary(MX.ToRadians)
abs_ = _unary(AR.Abs)
signum = _unary(AR.Signum)


def pow(a: ColumnOrName, b) -> Column:  # noqa: A001
    return Column(MX.Pow(_c(a), _to_expr(b)))


def log_base(base, c: ColumnOrName) -> Column:
    """log(base, x) (Spark's two-argument log)."""
    return Column(MX.Logarithm(_to_expr(base), _c(c)))


def atan2(a: ColumnOrName, b) -> Column:
    return Column(MX.Atan2(_c(a), _to_expr(b)))


def pmod(a: ColumnOrName, b) -> Column:
    return Column(AR.Pmod(_c(a), _to_expr(b)))


# -- bitwise -----------------------------------------------------------------
def shiftleft(c: ColumnOrName, n: int) -> Column:
    return Column(B.ShiftLeft(_c(c), Literal(n)))


def shiftright(c: ColumnOrName, n: int) -> Column:
    return Column(B.ShiftRight(_c(c), Literal(n)))


def shiftrightunsigned(c: ColumnOrName, n: int) -> Column:
    return Column(B.ShiftRightUnsigned(_c(c), Literal(n)))


def bitwise_not(c: ColumnOrName) -> Column:
    return Column(B.BitwiseNot(_c(c)))


# -- strings -----------------------------------------------------------------
def length(c: ColumnOrName) -> Column:
    return Column(S.Length(_c(c)))


def upper(c: ColumnOrName) -> Column:
    return Column(S.Upper(_c(c)))


def lower(c: ColumnOrName) -> Column:
    return Column(S.Lower(_c(c)))


def substring(c: ColumnOrName, pos: int, length_: int) -> Column:
    return Column(S.Substring(_c(c), Literal(pos), Literal(length_)))


def substring_index(c: ColumnOrName, delim: str, count: int) -> Column:
    return Column(S.SubstringIndex(_c(c), Literal(delim), Literal(count)))


def concat(*cols: ColumnOrName) -> Column:
    return Column(S.Concat(*[_c(c) for c in cols]))


def trim(c: ColumnOrName) -> Column:
    return Column(S.StringTrim(_c(c)))


def ltrim(c: ColumnOrName) -> Column:
    return Column(S.StringTrimLeft(_c(c)))


def rtrim(c: ColumnOrName) -> Column:
    return Column(S.StringTrimRight(_c(c)))


def regexp_replace(c: ColumnOrName, pattern: str, repl: str) -> Column:
    """regexp_replace; only literal (metacharacter-free) patterns run on
    device, mirroring the reference (GpuOverrides.scala:1458-1468)."""
    return Column(S.RegExpReplace(_c(c), Literal(pattern), Literal(repl)))


def locate(substr: str, c: ColumnOrName, pos: int = 1) -> Column:
    """1-based position of substr in c, 0 if absent (reference:
    GpuStringLocate, stringFunctions.scala:62)."""
    return Column(S.StringLocate(_c(c), Literal(substr), Literal(pos)))


def initcap(c: ColumnOrName) -> Column:
    return Column(S.InitCap(_c(c)))


def concat_ws(sep: str, *cols: ColumnOrName) -> Column:
    """Join non-null values with sep; returns '' (never NULL) when all
    inputs are null, matching Spark."""
    if not cols:
        raise ValueError("concat_ws requires at least one column")
    return Column(S.ConcatWs(sep, [_c(c) for c in cols]))


def replace(c: ColumnOrName, search: str, repl: str) -> Column:
    return Column(S.StringReplace(_c(c), Literal(search), Literal(repl)))


# -- datetime ----------------------------------------------------------------
year = _unary(DT.Year)
month = _unary(DT.Month)
dayofmonth = _unary(DT.DayOfMonth)
dayofweek = _unary(DT.DayOfWeek)
weekday = _unary(DT.WeekDay)
dayofyear = _unary(DT.DayOfYear)
quarter = _unary(DT.Quarter)
hour = _unary(DT.Hour)
minute = _unary(DT.Minute)
second = _unary(DT.Second)
last_day = _unary(DT.LastDay)


def datediff(end: ColumnOrName, start: ColumnOrName) -> Column:
    return Column(DT.DateDiff(_c(end), _c(start)))


def date_add(c: ColumnOrName, days) -> Column:
    return Column(DT.DateAdd(_c(c), _to_expr(days)))


def date_sub(c: ColumnOrName, days) -> Column:
    return Column(DT.DateSub(_c(c), _to_expr(days)))


def unix_timestamp(c: ColumnOrName) -> Column:
    return Column(DT.UnixTimestamp(_c(c)))


def to_unix_timestamp(c: ColumnOrName) -> Column:
    return Column(DT.ToUnixTimestamp(_c(c)))


def from_unixtime(c: ColumnOrName, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Column:
    return Column(DT.FromUnixTime(_c(c), Literal(fmt)))


# -- nondeterministic --------------------------------------------------------
def array(*cols: ColumnOrName) -> Column:
    """array(e1, e2, ...) — consumable only by explode()/posexplode()
    (reference: GpuGenerateExec supports Explode(CreateArray(...)) only,
    GpuGenerateExec.scala tagPlanForGpu)."""
    from spark_rapids_tpu.ops.generators import CreateArray

    return Column(CreateArray([_c(c) for c in cols]))


def explode(c: Column) -> Column:
    """One output row per array element per input row (reference:
    GpuGenerateExec.scala:101, includePos=false). Requires array(...)."""
    from spark_rapids_tpu.ops.generators import CreateArray, Explode

    e = _to_expr(c)
    if not isinstance(e, CreateArray):
        raise TypeError("explode() requires array(...) — arrays exist only "
                        "as created arrays (flat column types)")
    return Column(Explode(e))


def posexplode(c: Column) -> Column:
    """explode() plus the element position column (reference:
    GpuGenerateExec.scala:101, includePos=true)."""
    from spark_rapids_tpu.ops.generators import CreateArray, PosExplode

    e = _to_expr(c)
    if not isinstance(e, CreateArray):
        raise TypeError("posexplode() requires array(...)")
    return Column(PosExplode(e))


def rand(seed: int = 0) -> Column:
    return Column(MISC.Rand(seed))


def monotonically_increasing_id() -> Column:
    return Column(MISC.MonotonicallyIncreasingID())


def spark_partition_id() -> Column:
    return Column(MISC.SparkPartitionID())


def input_file_name() -> Column:
    return Column(MISC.InputFileName())


def input_file_block_start() -> Column:
    return Column(MISC.InputFileBlockStart())


def input_file_block_length() -> Column:
    return Column(MISC.InputFileBlockLength())


# -- aggregates --------------------------------------------------------------
def sum(c: ColumnOrName) -> Column:  # noqa: A001
    return Column(A.Sum(_c(c)))


def min(c: ColumnOrName) -> Column:  # noqa: A001
    return Column(A.Min(_c(c)))


def max(c: ColumnOrName) -> Column:  # noqa: A001
    return Column(A.Max(_c(c)))


def count(c: ColumnOrName = "*") -> Column:
    if isinstance(c, str) and c == "*":
        return Column(A.Count(Literal(1)))
    return Column(A.Count(_c(c)))


def percentile(c: ColumnOrName, p: float) -> Column:
    """Exact percentile at fraction p in [0, 1] (Spark `percentile`)."""
    return Column(A.Percentile(_c(c), p))


def avg(c: ColumnOrName) -> Column:
    return Column(A.Average(_c(c)))


mean = avg


def first(c: ColumnOrName, ignorenulls: bool = False) -> Column:
    return Column(A.First(_c(c), ignorenulls))


def last(c: ColumnOrName, ignorenulls: bool = False) -> Column:
    return Column(A.Last(_c(c), ignorenulls))


# -- window ------------------------------------------------------------------
def row_number() -> Column:
    from spark_rapids_tpu.ops.window import RowNumber

    return Column(RowNumber())


def rank() -> Column:
    from spark_rapids_tpu.ops.window import Rank

    return Column(Rank())


def dense_rank() -> Column:
    from spark_rapids_tpu.ops.window import DenseRank

    return Column(DenseRank())


def lead(c: ColumnOrName, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.ops.window import Lead

    return Column(Lead(_c(c), offset, default))


def lag(c: ColumnOrName, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.ops.window import Lag

    return Column(Lag(_c(c), offset, default))


def ntile(n: int) -> Column:
    from spark_rapids_tpu.ops.window import NTile

    return Column(NTile(n))
