"""Canonical plan signatures for the serving runtime (docs/serving.md).

A signature is a stable fingerprint of "what this query IS": the logical
plan's structure and expressions (with expression ids NORMALIZED to
first-appearance ordinals, so two structurally identical queries built
independently — fresh AttributeReference ids each — sign identically),
every leaf's schema, and the session's explicitly-set configuration (any
conf key can affect planning, so all of them key the signature; over-keying
can only cause a cache miss, never a wrong reuse).

Two flavors from one walk:

- `cache_key` additionally pins LEAF DATA IDENTITY (object identity of an
  in-memory relation's partition list; path + size + mtime of scanned
  files). It keys the plan cache (plan/plan_cache.py): a hit may reuse the
  cached physical plan outright, so it must be impossible for a query over
  different data to collide. Identity via id() is sound here because the
  cache entry holds the logical plan (and the physical plan holds the
  batches) strongly alive — a live entry's ids cannot be recycled.
- `shape_key` deliberately drops data identity: it groups look-alike
  queries over DIFFERENT data for cross-query micro-batching
  (engine/server.py).
"""

from __future__ import annotations

import hashlib
import os
import re
import zlib
from typing import Dict, List, Optional

from spark_rapids_tpu.plan import logical as L

# object.__repr__ leaks addresses; a canonical token must not
_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


class PlanSignature:
    __slots__ = ("cache_key", "shape_key")

    def __init__(self, cache_key: str, shape_key: str):
        self.cache_key = cache_key
        self.shape_key = shape_key

    def __repr__(self):
        return (f"PlanSignature(cache={self.cache_key[:12]}…, "
                f"shape={self.shape_key[:12]}…)")


def plan_signature(plan: "L.LogicalPlan",
                   conf) -> Optional[PlanSignature]:
    """Signature of (logical plan, conf), or None when the plan cannot be
    fingerprinted (an unexpected node/value shape — the caller simply
    skips caching)."""
    try:
        from spark_rapids_tpu import conf as C

        conf_tok = ";".join(
            f"{k}={v!r}" for k, v in sorted(
                conf.settings.items(), key=lambda kv: str(kv[0])))
        # the RESOLVED adaptive flag keys the signature even when it is
        # defaulted: a cached static plan must never serve an adaptive
        # query (or vice versa) — the adaptive plan carries the
        # TpuAdaptiveExec wrapper and re-optimizes at runtime
        conf_tok += f";__adaptive={bool(conf.get(C.ADAPTIVE_ENABLED))!r}"
        # same for the RESOLVED spmd flag (default ON since r14): the
        # lowered plan carries TpuSpmdStageExec wrappers a host-loop
        # query must never be served
        conf_tok += f";__spmd={bool(conf.get(C.SPMD_ENABLED))!r}"
        # the placement pass keys on the FITTED MODELS, not just the
        # conf: warming either model must invalidate the cached
        # all-device plan, so the model fit stamps join the token
        if conf.get(C.PLACEMENT_ENABLED):
            from spark_rapids_tpu.obs import calibrate as CAL

            dm = CAL.active_model()
            hm = CAL.active_host_model()
            conf_tok += (
                f";__placement={conf.get(C.PLACEMENT_MODE)}"
                f":{conf.get(C.PLACEMENT_MIN_SAMPLES)}"
                f":{0 if dm is None else dm.fitted_at_ns}"
                f":{0 if hm is None else hm.fitted_at_ns}")
        idmap: Dict[int, int] = {}
        ident = _canon_node(plan, idmap, identity=True)
        idmap = {}
        shape = _canon_node(plan, idmap, identity=False)
    except Exception:  # noqa: BLE001 - best-effort fingerprint
        return None
    return PlanSignature(
        cache_key=_digest(ident + "||" + conf_tok),
        shape_key=_digest(shape + "||" + conf_tok),
    )


def _digest(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8", "surrogatepass")).hexdigest()


# ---------------------------------------------------------------------------
# Node canonicalization
# ---------------------------------------------------------------------------
def _canon_node(p: "L.LogicalPlan", idmap: Dict[int, int],
                identity: bool) -> str:
    name = type(p).__name__
    if isinstance(p, L.LocalRelation):
        schema = _canon_val(p.schema, idmap)
        tok = f"{name}({schema};nparts={len(p.partitions)}"
        if identity:
            # object identity of the node AND its partitions list: the
            # cache entry keeps both alive (see module docstring), so a
            # live id can never be recycled into a false hit
            tok += f";data={id(p)}/{id(p.partitions)}"
        return tok + ")"
    if isinstance(p, L.FileScan):
        files = list(p.files or [])
        tok = (f"{name}(fmt={p.fmt};paths={sorted(p.paths)!r};"
               f"opts={sorted((str(k), repr(v)) for k, v in p.options.items())!r};"
               f"schema={_canon_val(p.schema, idmap)}")
        if identity:
            tok += f";files={_file_fingerprints(files or p.paths)!r}"
        return tok + ")"
    if isinstance(p, L.CacheRelation):
        child = _canon_node(p.children[0], idmap, identity)
        # a cached relation's materialization is keyed by node identity
        # (exec/cache.py); identity mode must carry it so two different
        # cached datasets with identical shapes never share a plan
        ident = f";cache={id(p)}" if identity else ""
        return f"{name}({child}{ident})"
    # generic node: scalar/expression state from __dict__ (children
    # excluded — they canonicalize recursively below)
    state = []
    for k in sorted(vars(p)):
        if k == "children":
            continue
        state.append(f"{k}={_canon_val(vars(p)[k], idmap)}")
    kids = ",".join(_canon_node(c, idmap, identity) for c in p.children)
    return f"{name}({';'.join(state)})[{kids}]"


def _file_fingerprints(paths: List[str]) -> List[tuple]:
    out = []
    for f in paths:
        try:
            st = os.stat(f)
            out.append((f, st.st_size, st.st_mtime_ns))
        except OSError:
            out.append((f, "?"))
    return out


# ---------------------------------------------------------------------------
# Value / expression canonicalization
# ---------------------------------------------------------------------------
def _canon_val(v, idmap: Dict[int, int]) -> str:
    import numpy as np

    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return repr(v)
    if isinstance(v, np.generic):
        return f"np({v.dtype}:{v!r})"
    if isinstance(v, np.ndarray):
        return (f"nd({v.dtype}:{v.shape}:"
                f"{zlib.crc32(np.ascontiguousarray(v).tobytes()):08x})")
    if isinstance(v, (list, tuple)):
        inner = ",".join(_canon_val(x, idmap) for x in v)
        return f"[{inner}]" if isinstance(v, list) else f"({inner})"
    if isinstance(v, dict):
        inner = ",".join(
            f"{_canon_val(k, idmap)}:{_canon_val(x, idmap)}"
            for k, x in sorted(v.items(), key=lambda kv: str(kv[0])))
        return f"{{{inner}}}"
    if isinstance(v, type):
        return f"type:{v.__name__}"
    d = getattr(v, "__dict__", None)
    if d is not None:
        state = []
        for k in sorted(d):
            if k == "expr_id":
                # normalize to first-appearance ordinal: identity
                # RELATIONSHIPS (same id -> same token) survive, the
                # per-process counter values do not
                state.append(
                    f"expr_id=${idmap.setdefault(d[k], len(idmap))}")
            else:
                state.append(f"{k}={_canon_val(d[k], idmap)}")
        return f"{type(v).__name__}({';'.join(state)})"
    # enums / slotted immutables: their repr is stable; scrub addresses so
    # a default object.__repr__ can never leak one into the signature
    return _ADDR_RE.sub("", repr(v))
