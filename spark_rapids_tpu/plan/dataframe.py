"""DataFrame API over the logical plan (the pyspark.sql.DataFrame analog).

The reference accelerates plans produced by Spark's DataFrame/SQL API; this
standalone framework supplies the equivalent user surface. Name resolution
(`col("x")` -> AttributeReference) happens here, eagerly, against the child
plan's output — the analog of Catalyst's analyzer for this flat algebra.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.aggregates import AggregateFunction
from spark_rapids_tpu.ops.base import (
    Alias,
    AttributeReference,
    Expression,
    SortOrder,
    to_attribute,
)
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.column import Column, _to_expr, to_sort_order
from spark_rapids_tpu.plan.functions import _UnresolvedAttribute

ColumnOrName = Union[Column, str]


class AnalysisError(Exception):
    pass


def resolve(expr: Expression, attrs: Sequence[AttributeReference]) -> Expression:
    """Rewrite _UnresolvedAttribute leaves into schema attributes."""
    by_name: Dict[str, AttributeReference] = {}
    dupes = set()
    for a in attrs:
        if a.name in by_name:
            dupes.add(a.name)
        by_name.setdefault(a.name, a)

    def rewrite(node: Expression) -> Expression:
        if isinstance(node, _UnresolvedAttribute):
            if node.name in dupes:
                raise AnalysisError(
                    f"ambiguous column {node.name!r}; rename before combining")
            got = by_name.get(node.name)
            if got is None:
                raise AnalysisError(
                    f"column {node.name!r} not found in "
                    f"[{', '.join(a.name for a in attrs)}]")
            return got
        return node

    return expr.transform_up(rewrite)


def _auto_alias(e: Expression, fallback: str) -> Expression:
    if isinstance(e, (Alias, AttributeReference)):
        return e
    return Alias(e, fallback)


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session):
        self._plan = plan
        self.session = session

    # -- schema ---------------------------------------------------------------
    @property
    def schema(self) -> List[AttributeReference]:
        return self._plan.output

    @property
    def columns(self) -> List[str]:
        return [a.name for a in self._plan.output]

    def __getitem__(self, name: str) -> Column:
        return Column(self._resolve_name(name))

    def _resolve_name(self, name: str) -> AttributeReference:
        for a in self._plan.output:
            if a.name == name:
                return a
        raise AnalysisError(
            f"column {name!r} not found in [{', '.join(self.columns)}]")

    def _resolve(self, c: ColumnOrName) -> Expression:
        if isinstance(c, str):
            if c == "*":
                raise AnalysisError("'*' only valid inside select()")
            return self._resolve_name(c)
        return resolve(_to_expr(c), self._plan.output)

    def _with_plan(self, plan: L.LogicalPlan) -> "DataFrame":
        return DataFrame(plan, self.session)

    # -- relational ops -------------------------------------------------------
    def select(self, *cols: ColumnOrName) -> "DataFrame":
        from spark_rapids_tpu.ops.generators import Explode

        out: List[Expression] = []
        gen: Optional[Expression] = None
        gen_slot = -1
        for c in cols:
            if isinstance(c, str) and c == "*":
                out.extend(self._plan.output)
                continue
            e = self._resolve(c)
            core = e.child if isinstance(e, Alias) else e
            if isinstance(core, Explode):
                if gen is not None:
                    raise ValueError("only one explode()/posexplode() per "
                                     "select (Spark restriction)")
                gen = e
                gen_slot = len(out)
                out.append(e)  # placeholder, replaced below
                continue
            out.append(_auto_alias(e, self._default_name(c, len(out))))
        if gen is None:
            return self._with_plan(L.Project(out, self._plan))
        return self._select_generate(out, gen, gen_slot)

    def _select_generate(self, out: List[Expression], gen: Expression,
                         gen_slot: int) -> "DataFrame":
        """Lower select(..., explode(array(...)), ...) to Generate + Project
        (reference: GpuGenerateExec replacing GenerateExec of
        Explode(CreateArray), GpuGenerateExec.scala)."""
        from spark_rapids_tpu.ops.cast import Cast
        from spark_rapids_tpu.ops.generators import Explode

        alias_name = gen.name if isinstance(gen, Alias) else None
        core: Explode = gen.child if isinstance(gen, Alias) else gen
        elem_t = core.array.element_type
        elems = [e if e.data_type is elem_t else Cast(e, elem_t)
                 for e in core.array.elems]
        generator = core.with_children([core.array.with_children(elems)])
        gen_attrs: List[AttributeReference] = []
        if core.include_pos:
            if alias_name is not None:
                raise ValueError("posexplode produces two columns (pos, col)"
                                 " and cannot be aliased to one name")
            gen_attrs.append(AttributeReference("pos", DataType.INT32, False))
        gen_attrs.append(AttributeReference(
            alias_name or "col", elem_t, True))
        plan = L.Generate(generator, gen_attrs, False, self._plan)
        final = out[:gen_slot] + list(gen_attrs) + out[gen_slot + 1:]
        return self._with_plan(L.Project(final, plan))

    @staticmethod
    def _default_name(c: ColumnOrName, idx: int) -> str:
        if isinstance(c, str):
            return c
        return f"col{idx}"

    def withColumn(self, name: str, c: Column) -> "DataFrame":
        e = Alias(self._resolve(c), name)
        out: List[Expression] = []
        replaced = False
        for a in self._plan.output:
            if a.name == name:
                out.append(e)
                replaced = True
            else:
                out.append(a)
        if not replaced:
            out.append(e)
        return self._with_plan(L.Project(out, self._plan))

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        out = [Alias(a, new) if a.name == old else a for a in self._plan.output]
        return self._with_plan(L.Project(out, self._plan))

    def drop(self, *names: str) -> "DataFrame":
        keep = [a for a in self._plan.output if a.name not in names]
        return self._with_plan(L.Project(keep, self._plan))

    def filter(self, condition: Union[Column, str]) -> "DataFrame":
        if isinstance(condition, str):
            raise AnalysisError("string predicates require the SQL frontend; "
                                "pass a Column")
        return self._with_plan(
            L.Filter(self._resolve(condition), self._plan))

    where = filter

    def limit(self, n: int) -> "DataFrame":
        return self._with_plan(L.Limit(n, self._plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        if len(other.schema) != len(self.schema):
            raise AnalysisError("union requires same number of columns")
        return self._with_plan(L.Union(self._plan, other._plan))

    unionAll = union

    def distinct(self) -> "DataFrame":
        attrs = self._plan.output
        return self._with_plan(L.Aggregate(list(attrs), list(attrs), self._plan))

    def dropDuplicates(self, subset: Optional[List[str]] = None) -> "DataFrame":
        if not subset:
            return self.distinct()
        keys = [self._resolve_name(n) for n in subset]
        from spark_rapids_tpu.ops.aggregates import First

        aggs: List[Expression] = []
        for a in self._plan.output:
            if a.name in subset:
                aggs.append(a)
            else:
                aggs.append(Alias(First(a), a.name))
        return self._with_plan(L.Aggregate(keys, aggs, self._plan))

    def repartition(self, num_partitions: int, *cols: ColumnOrName) -> "DataFrame":
        exprs = [self._resolve(c) for c in cols]
        return self._with_plan(
            L.Repartition(num_partitions, exprs, False, self._plan))

    def coalesce(self, num_partitions: int) -> "DataFrame":
        return self._with_plan(
            L.Repartition(num_partitions, [], True, self._plan))

    def orderBy(self, *cols, **kwargs) -> "DataFrame":
        orders = []
        ascending = kwargs.get("ascending", True)
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(SortOrder(resolve(c.child, self._plan.output),
                                        c.ascending, c.nulls_first))
            elif isinstance(c, str):
                orders.append(SortOrder(self._resolve_name(c), ascending))
            else:
                orders.append(SortOrder(self._resolve(c), ascending))
        return self._with_plan(L.Sort(orders, True, self._plan))

    sort = orderBy

    def sortWithinPartitions(self, *cols, **kwargs) -> "DataFrame":
        df = self.orderBy(*cols, **kwargs)
        plan = df._plan
        assert isinstance(plan, L.Sort)
        return self._with_plan(L.Sort(plan.orders, False, self._plan))

    # -- aggregation ----------------------------------------------------------
    def groupBy(self, *cols: ColumnOrName) -> "GroupedData":
        keys = [self._resolve(c) for c in cols]
        named = [_auto_alias(k, self._default_name(c, i))
                 for i, (k, c) in enumerate(zip(keys, cols))]
        return GroupedData(self, named)

    groupby = groupBy

    def rollup(self, *cols: ColumnOrName) -> "GroupedData":
        """Hierarchical grouping sets (a,b) -> {(a,b), (a), ()} lowered
        through Expand (reference: GpuExpandExec.scala:66-102)."""
        g = self.groupBy(*cols)
        m = len(g._grouping)
        g._grouping_sets = [frozenset(range(k)) for k in range(m, -1, -1)]
        return g

    def cube(self, *cols: ColumnOrName) -> "GroupedData":
        """All 2^m grouping-set combinations lowered through Expand."""
        import itertools as _it

        g = self.groupBy(*cols)
        m = len(g._grouping)
        g._grouping_sets = [
            frozenset(s)
            for k in range(m, -1, -1)
            for s in _it.combinations(range(m), k)
        ]
        return g

    def agg(self, *cols: Column) -> "DataFrame":
        return GroupedData(self, []).agg(*cols)

    def count(self) -> int:
        from spark_rapids_tpu.plan.functions import count as f_count

        rows = self.agg(f_count("*").alias("count")).collect()
        return rows[0][0]

    # -- joins ----------------------------------------------------------------
    def join(self, other: "DataFrame",
             on: Union[str, List[str], Column, None] = None,
             how: str = "inner") -> "DataFrame":
        jt = L.JoinType.parse(how)
        left_keys: List[Expression] = []
        right_keys: List[Expression] = []
        condition: Optional[Expression] = None
        if isinstance(on, str):
            on = [on]
        if isinstance(on, list):
            for name in on:
                left_keys.append(self._resolve_name(name))
                right_keys.append(other._resolve_name(name))
        elif isinstance(on, Column):
            condition = self._resolve_join_condition(on, other)
            left_keys, right_keys, condition = _extract_equi_keys(
                condition, self._plan.output, other._plan.output)
        elif on is not None:
            raise AnalysisError(f"unsupported join on: {on!r}")
        elif jt is not L.JoinType.CROSS:
            raise AnalysisError("join requires 'on' unless how='cross'")
        plan = L.Join(self._plan, other._plan, jt, left_keys, right_keys,
                      condition)
        df = self._with_plan(plan)
        if isinstance(on, list) and jt in (
                L.JoinType.INNER, L.JoinType.LEFT_OUTER,
                L.JoinType.RIGHT_OUTER, L.JoinType.FULL_OUTER):
            # USING-join semantics: emit the join columns once
            drop_ids = {a.expr_id for a in right_keys
                        if isinstance(a, AttributeReference)}
            keep = [a for a in plan.output if a.expr_id not in drop_ids]
            df = df._with_plan(L.Project(keep, plan))
        return df

    def _resolve_join_condition(self, c: Column, other: "DataFrame") -> Expression:
        both = list(self._plan.output) + list(other._plan.output)
        return resolve(c.expr, both)

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return self.join(other, on=None, how="cross")

    # -- caching --------------------------------------------------------------
    def cache(self) -> "DataFrame":
        """Cache this DataFrame's batches in memory (device-resident on the
        TPU engine; reference: df.cache() served by the accelerated
        InMemoryTableScan path)."""
        if isinstance(self._plan, L.CacheRelation):
            return self
        return self._with_plan(L.CacheRelation(self._plan))

    persist = cache

    def unpersist(self) -> "DataFrame":
        from spark_rapids_tpu.exec.cache import invalidate

        if isinstance(self._plan, L.CacheRelation):
            invalidate(self._plan)
            return self._with_plan(self._plan.children[0])
        return self

    # -- actions --------------------------------------------------------------
    def collect(self, timeout=None) -> List[tuple]:
        """Run the query and return all rows. `timeout` (seconds) arms a
        per-call deadline on the query's CancelToken — overriding
        rapids.tpu.engine.deadlineMs — after which the query raises
        TpuDeadlineExceeded with no partial rows and releases everything
        it holds (docs/fault-tolerance.md)."""
        return self.session.execute_collect(self._plan, timeout_s=timeout)

    def toLocalBatches(self):
        return self.session.execute_batches(self._plan)

    def show(self, n: int = 20) -> None:
        rows = self.limit(n).collect()
        names = self.columns
        # tpulint: stdout-print -- show() IS the console API
        print(" | ".join(names))
        for r in rows:
            # tpulint: stdout-print -- show() IS the console API
            print(" | ".join(str(v) for v in r))

    def explain(self, mode: str = "ALL") -> str:
        text = self.session.explain_plan(self._plan, mode)
        # tpulint: stdout-print -- explain() IS the console API
        print(text)
        return text

    def explain_analyze(self) -> str:
        """EXPLAIN ANALYZE: execute this query (tracing forced on) and
        print the plan annotated with measured per-operator metrics
        beside the analyzer's predictions (docs/observability.md)."""
        text = self.session.explain_analyze(self._plan)
        # tpulint: stdout-print -- explain_analyze() IS the console API
        print(text)
        return text

    def toPandas(self):
        import pandas as pd

        rows = self.collect()
        return pd.DataFrame(rows, columns=self.columns)

    # -- write ----------------------------------------------------------------
    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    @property
    def rdd_columnar(self):
        """Device-resident columnar export (reference: ColumnarRdd.scala —
        DataFrame -> RDD[Table] handoff for ML)."""
        from spark_rapids_tpu.integration.columnar_rdd import columnar_rdd

        return columnar_rdd(self)


class GroupedData:
    def __init__(self, df: DataFrame, grouping: List[Expression]):
        self._df = df
        self._grouping = grouping
        # rollup/cube: list of frozensets of grouping-column ordinals
        self._grouping_sets: Optional[List[frozenset]] = None

    def agg(self, *cols: Column) -> DataFrame:
        if self._grouping_sets is not None:
            return self._agg_grouping_sets(cols)
        out: List[Expression] = list(self._grouping)
        for i, c in enumerate(cols):
            e = resolve(_to_expr(c), self._df._plan.output)
            out.append(_auto_alias(e, f"agg{i}"))
        plan = L.Aggregate([to_attribute(g) if isinstance(g, Alias) else g
                            for g in self._grouping], out, self._df._plan)
        return self._df._with_plan(plan)

    def _agg_grouping_sets(self, cols) -> DataFrame:
        """rollup/cube: Expand emits one copy of the input per grouping set
        (null-filled dropped keys + a grouping id that keeps natural nulls
        distinct from rolled-up nulls), then a regular aggregate groups on
        the expanded keys + id (reference: GpuExpandExec feeding
        GpuHashAggregateExec, GpuExpandExec.scala:66-102)."""
        from spark_rapids_tpu.ops.literals import Literal

        child = self._df._plan
        m = len(self._grouping)
        g_exprs = [g.child if isinstance(g, Alias) else g
                   for g in self._grouping]
        g_names = [to_attribute(g).name if isinstance(g, Alias) else g.name
                   for g in self._grouping]
        g_types = [g.data_type for g in g_exprs]
        # fresh output attrs for the expanded keys (nullable: sets null them)
        key_attrs = [AttributeReference(n, t, True)
                     for n, t in zip(g_names, g_types)]
        gid_attr = AttributeReference("spark_grouping_id", DataType.INT32,
                                      False)
        projections: List[List[Expression]] = []
        for s in self._grouping_sets:
            gid = 0
            proj: List[Expression] = list(child.output)
            for i in range(m):
                if i in s:
                    proj.append(g_exprs[i])
                else:
                    proj.append(Literal(None, g_types[i]))
                    gid |= 1 << (m - 1 - i)
            proj.append(Literal(gid, DataType.INT32))
            projections.append(proj)
        expand_out = list(child.output) + key_attrs + [gid_attr]
        expand = L.Expand(projections, expand_out, child)
        out: List[Expression] = [Alias(a, a.name) for a in key_attrs]
        for i, c in enumerate(cols):
            e = resolve(_to_expr(c), child.output)
            out.append(_auto_alias(e, f"agg{i}"))
        # gid is grouping-only (not in agg_exprs), so the Aggregate's output
        # is already the user-visible schema
        plan = L.Aggregate(key_attrs + [gid_attr], out, expand)
        return self._df._with_plan(plan)

    def _simple(self, fn, *cols: str) -> DataFrame:
        from spark_rapids_tpu.plan import functions as F

        names = cols or [a.name for a in self._df.schema
                         if a.data_type.is_numeric]
        return self.agg(*[getattr(F, fn)(n).alias(f"{fn}({n})") for n in names])

    def sum(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("sum", *cols)

    def min(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("min", *cols)

    def max(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("max", *cols)

    def avg(self, *cols: str) -> DataFrame:
        return self._simple("avg", *cols)

    mean = avg

    def count(self) -> DataFrame:
        from spark_rapids_tpu.plan.functions import count as f_count

        return self.agg(f_count("*").alias("count"))


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self._df = df
        self._mode = "error"
        self._options: Dict[str, Any] = {}
        self._partition_by: List[str] = []

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, k: str, v: Any) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def parquet(self, path: str) -> None:
        self._write("parquet", path)

    def orc(self, path: str) -> None:
        self._write("orc", path)

    def csv(self, path: str) -> None:
        self._write("csv", path)

    def _write(self, fmt: str, path: str) -> None:
        plan = L.WriteFile(fmt, path, self._mode, self._options,
                           self._partition_by, self._df._plan)
        self._df.session.execute_write(plan)


def _extract_equi_keys(condition: Expression, left_attrs, right_attrs):
    """Split a join condition into equi-key pairs + residual condition
    (the planner's extractEquiJoinKeys analog)."""
    from spark_rapids_tpu.ops.predicates import And, EqualTo

    left_ids = {a.expr_id for a in left_attrs}
    right_ids = {a.expr_id for a in right_attrs}

    def refs(e: Expression):
        return {n.expr_id for n in e.collect(
            lambda x: isinstance(x, AttributeReference))}

    conjuncts: List[Expression] = []

    def split(e: Expression):
        if isinstance(e, And):
            split(e.left)
            split(e.right)
        else:
            conjuncts.append(e)

    split(condition)
    lk, rk, residual = [], [], []
    for c in conjuncts:
        if isinstance(c, EqualTo):
            lrefs, rrefs = refs(c.left), refs(c.right)
            if lrefs <= left_ids and rrefs <= right_ids:
                lk.append(c.left)
                rk.append(c.right)
                continue
            if lrefs <= right_ids and rrefs <= left_ids:
                lk.append(c.right)
                rk.append(c.left)
                continue
        residual.append(c)
    cond: Optional[Expression] = None
    for r in residual:
        cond = r if cond is None else And(cond, r)
    return lk, rk, cond
