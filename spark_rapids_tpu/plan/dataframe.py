"""DataFrame API over the logical plan (the pyspark.sql.DataFrame analog).

The reference accelerates plans produced by Spark's DataFrame/SQL API; this
standalone framework supplies the equivalent user surface. Name resolution
(`col("x")` -> AttributeReference) happens here, eagerly, against the child
plan's output — the analog of Catalyst's analyzer for this flat algebra.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.aggregates import AggregateFunction
from spark_rapids_tpu.ops.base import (
    Alias,
    AttributeReference,
    Expression,
    SortOrder,
    to_attribute,
)
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.column import Column, _to_expr, to_sort_order
from spark_rapids_tpu.plan.functions import _UnresolvedAttribute

ColumnOrName = Union[Column, str]


class AnalysisError(Exception):
    pass


def resolve(expr: Expression, attrs: Sequence[AttributeReference]) -> Expression:
    """Rewrite _UnresolvedAttribute leaves into schema attributes."""
    by_name: Dict[str, AttributeReference] = {}
    dupes = set()
    for a in attrs:
        if a.name in by_name:
            dupes.add(a.name)
        by_name.setdefault(a.name, a)

    def rewrite(node: Expression) -> Expression:
        if isinstance(node, _UnresolvedAttribute):
            if node.name in dupes:
                raise AnalysisError(
                    f"ambiguous column {node.name!r}; rename before combining")
            got = by_name.get(node.name)
            if got is None:
                raise AnalysisError(
                    f"column {node.name!r} not found in "
                    f"[{', '.join(a.name for a in attrs)}]")
            return got
        return node

    return expr.transform_up(rewrite)


def _auto_alias(e: Expression, fallback: str) -> Expression:
    if isinstance(e, (Alias, AttributeReference)):
        return e
    return Alias(e, fallback)


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session):
        self._plan = plan
        self.session = session

    # -- schema ---------------------------------------------------------------
    @property
    def schema(self) -> List[AttributeReference]:
        return self._plan.output

    @property
    def columns(self) -> List[str]:
        return [a.name for a in self._plan.output]

    def __getitem__(self, name: str) -> Column:
        return Column(self._resolve_name(name))

    def _resolve_name(self, name: str) -> AttributeReference:
        for a in self._plan.output:
            if a.name == name:
                return a
        raise AnalysisError(
            f"column {name!r} not found in [{', '.join(self.columns)}]")

    def _resolve(self, c: ColumnOrName) -> Expression:
        if isinstance(c, str):
            if c == "*":
                raise AnalysisError("'*' only valid inside select()")
            return self._resolve_name(c)
        return resolve(_to_expr(c), self._plan.output)

    def _with_plan(self, plan: L.LogicalPlan) -> "DataFrame":
        return DataFrame(plan, self.session)

    # -- relational ops -------------------------------------------------------
    def select(self, *cols: ColumnOrName) -> "DataFrame":
        out: List[Expression] = []
        for c in cols:
            if isinstance(c, str) and c == "*":
                out.extend(self._plan.output)
                continue
            e = self._resolve(c)
            out.append(_auto_alias(e, self._default_name(c, len(out))))
        return self._with_plan(L.Project(out, self._plan))

    @staticmethod
    def _default_name(c: ColumnOrName, idx: int) -> str:
        if isinstance(c, str):
            return c
        return f"col{idx}"

    def withColumn(self, name: str, c: Column) -> "DataFrame":
        e = Alias(self._resolve(c), name)
        out: List[Expression] = []
        replaced = False
        for a in self._plan.output:
            if a.name == name:
                out.append(e)
                replaced = True
            else:
                out.append(a)
        if not replaced:
            out.append(e)
        return self._with_plan(L.Project(out, self._plan))

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        out = [Alias(a, new) if a.name == old else a for a in self._plan.output]
        return self._with_plan(L.Project(out, self._plan))

    def drop(self, *names: str) -> "DataFrame":
        keep = [a for a in self._plan.output if a.name not in names]
        return self._with_plan(L.Project(keep, self._plan))

    def filter(self, condition: Union[Column, str]) -> "DataFrame":
        if isinstance(condition, str):
            raise AnalysisError("string predicates require the SQL frontend; "
                                "pass a Column")
        return self._with_plan(
            L.Filter(self._resolve(condition), self._plan))

    where = filter

    def limit(self, n: int) -> "DataFrame":
        return self._with_plan(L.Limit(n, self._plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        if len(other.schema) != len(self.schema):
            raise AnalysisError("union requires same number of columns")
        return self._with_plan(L.Union(self._plan, other._plan))

    unionAll = union

    def distinct(self) -> "DataFrame":
        attrs = self._plan.output
        return self._with_plan(L.Aggregate(list(attrs), list(attrs), self._plan))

    def dropDuplicates(self, subset: Optional[List[str]] = None) -> "DataFrame":
        if not subset:
            return self.distinct()
        keys = [self._resolve_name(n) for n in subset]
        from spark_rapids_tpu.ops.aggregates import First

        aggs: List[Expression] = []
        for a in self._plan.output:
            if a.name in subset:
                aggs.append(a)
            else:
                aggs.append(Alias(First(a), a.name))
        return self._with_plan(L.Aggregate(keys, aggs, self._plan))

    def repartition(self, num_partitions: int, *cols: ColumnOrName) -> "DataFrame":
        exprs = [self._resolve(c) for c in cols]
        return self._with_plan(
            L.Repartition(num_partitions, exprs, False, self._plan))

    def coalesce(self, num_partitions: int) -> "DataFrame":
        return self._with_plan(
            L.Repartition(num_partitions, [], True, self._plan))

    def orderBy(self, *cols, **kwargs) -> "DataFrame":
        orders = []
        ascending = kwargs.get("ascending", True)
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(SortOrder(resolve(c.child, self._plan.output),
                                        c.ascending, c.nulls_first))
            elif isinstance(c, str):
                orders.append(SortOrder(self._resolve_name(c), ascending))
            else:
                orders.append(SortOrder(self._resolve(c), ascending))
        return self._with_plan(L.Sort(orders, True, self._plan))

    sort = orderBy

    def sortWithinPartitions(self, *cols, **kwargs) -> "DataFrame":
        df = self.orderBy(*cols, **kwargs)
        plan = df._plan
        assert isinstance(plan, L.Sort)
        return self._with_plan(L.Sort(plan.orders, False, self._plan))

    # -- aggregation ----------------------------------------------------------
    def groupBy(self, *cols: ColumnOrName) -> "GroupedData":
        keys = [self._resolve(c) for c in cols]
        named = [_auto_alias(k, self._default_name(c, i))
                 for i, (k, c) in enumerate(zip(keys, cols))]
        return GroupedData(self, named)

    groupby = groupBy

    def agg(self, *cols: Column) -> "DataFrame":
        return GroupedData(self, []).agg(*cols)

    def count(self) -> int:
        from spark_rapids_tpu.plan.functions import count as f_count

        rows = self.agg(f_count("*").alias("count")).collect()
        return rows[0][0]

    # -- joins ----------------------------------------------------------------
    def join(self, other: "DataFrame",
             on: Union[str, List[str], Column, None] = None,
             how: str = "inner") -> "DataFrame":
        jt = L.JoinType.parse(how)
        left_keys: List[Expression] = []
        right_keys: List[Expression] = []
        condition: Optional[Expression] = None
        if isinstance(on, str):
            on = [on]
        if isinstance(on, list):
            for name in on:
                left_keys.append(self._resolve_name(name))
                right_keys.append(other._resolve_name(name))
        elif isinstance(on, Column):
            condition = self._resolve_join_condition(on, other)
            left_keys, right_keys, condition = _extract_equi_keys(
                condition, self._plan.output, other._plan.output)
        elif on is not None:
            raise AnalysisError(f"unsupported join on: {on!r}")
        elif jt is not L.JoinType.CROSS:
            raise AnalysisError("join requires 'on' unless how='cross'")
        plan = L.Join(self._plan, other._plan, jt, left_keys, right_keys,
                      condition)
        df = self._with_plan(plan)
        if isinstance(on, list) and jt in (
                L.JoinType.INNER, L.JoinType.LEFT_OUTER,
                L.JoinType.RIGHT_OUTER, L.JoinType.FULL_OUTER):
            # USING-join semantics: emit the join columns once
            drop_ids = {a.expr_id for a in right_keys
                        if isinstance(a, AttributeReference)}
            keep = [a for a in plan.output if a.expr_id not in drop_ids]
            df = df._with_plan(L.Project(keep, plan))
        return df

    def _resolve_join_condition(self, c: Column, other: "DataFrame") -> Expression:
        both = list(self._plan.output) + list(other._plan.output)
        return resolve(c.expr, both)

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return self.join(other, on=None, how="cross")

    # -- caching --------------------------------------------------------------
    def cache(self) -> "DataFrame":
        """Cache this DataFrame's batches in memory (device-resident on the
        TPU engine; reference: df.cache() served by the accelerated
        InMemoryTableScan path)."""
        if isinstance(self._plan, L.CacheRelation):
            return self
        return self._with_plan(L.CacheRelation(self._plan))

    persist = cache

    def unpersist(self) -> "DataFrame":
        from spark_rapids_tpu.exec.cache import invalidate

        if isinstance(self._plan, L.CacheRelation):
            invalidate(self._plan)
            return self._with_plan(self._plan.children[0])
        return self

    # -- actions --------------------------------------------------------------
    def collect(self) -> List[tuple]:
        return self.session.execute_collect(self._plan)

    def toLocalBatches(self):
        return self.session.execute_batches(self._plan)

    def show(self, n: int = 20) -> None:
        rows = self.limit(n).collect()
        names = self.columns
        print(" | ".join(names))
        for r in rows:
            print(" | ".join(str(v) for v in r))

    def explain(self, mode: str = "ALL") -> str:
        text = self.session.explain_plan(self._plan, mode)
        print(text)
        return text

    def toPandas(self):
        import pandas as pd

        rows = self.collect()
        return pd.DataFrame(rows, columns=self.columns)

    # -- write ----------------------------------------------------------------
    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    @property
    def rdd_columnar(self):
        """Device-resident columnar export (reference: ColumnarRdd.scala —
        DataFrame -> RDD[Table] handoff for ML)."""
        from spark_rapids_tpu.integration.columnar_rdd import columnar_rdd

        return columnar_rdd(self)


class GroupedData:
    def __init__(self, df: DataFrame, grouping: List[Expression]):
        self._df = df
        self._grouping = grouping

    def agg(self, *cols: Column) -> DataFrame:
        out: List[Expression] = list(self._grouping)
        for i, c in enumerate(cols):
            e = resolve(_to_expr(c), self._df._plan.output)
            out.append(_auto_alias(e, f"agg{i}"))
        plan = L.Aggregate([to_attribute(g) if isinstance(g, Alias) else g
                            for g in self._grouping], out, self._df._plan)
        return self._df._with_plan(plan)

    def _simple(self, fn, *cols: str) -> DataFrame:
        from spark_rapids_tpu.plan import functions as F

        names = cols or [a.name for a in self._df.schema
                         if a.data_type.is_numeric]
        return self.agg(*[getattr(F, fn)(n).alias(f"{fn}({n})") for n in names])

    def sum(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("sum", *cols)

    def min(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("min", *cols)

    def max(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("max", *cols)

    def avg(self, *cols: str) -> DataFrame:
        return self._simple("avg", *cols)

    mean = avg

    def count(self) -> DataFrame:
        from spark_rapids_tpu.plan.functions import count as f_count

        return self.agg(f_count("*").alias("count"))


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self._df = df
        self._mode = "error"
        self._options: Dict[str, Any] = {}
        self._partition_by: List[str] = []

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, k: str, v: Any) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def parquet(self, path: str) -> None:
        self._write("parquet", path)

    def orc(self, path: str) -> None:
        self._write("orc", path)

    def csv(self, path: str) -> None:
        self._write("csv", path)

    def _write(self, fmt: str, path: str) -> None:
        plan = L.WriteFile(fmt, path, self._mode, self._options,
                           self._partition_by, self._df._plan)
        self._df.session.execute_write(plan)


def _extract_equi_keys(condition: Expression, left_attrs, right_attrs):
    """Split a join condition into equi-key pairs + residual condition
    (the planner's extractEquiJoinKeys analog)."""
    from spark_rapids_tpu.ops.predicates import And, EqualTo

    left_ids = {a.expr_id for a in left_attrs}
    right_ids = {a.expr_id for a in right_attrs}

    def refs(e: Expression):
        return {n.expr_id for n in e.collect(
            lambda x: isinstance(x, AttributeReference))}

    conjuncts: List[Expression] = []

    def split(e: Expression):
        if isinstance(e, And):
            split(e.left)
            split(e.right)
        else:
            conjuncts.append(e)

    split(condition)
    lk, rk, residual = [], [], []
    for c in conjuncts:
        if isinstance(c, EqualTo):
            lrefs, rrefs = refs(c.left), refs(c.right)
            if lrefs <= left_ids and rrefs <= right_ids:
                lk.append(c.left)
                rk.append(c.right)
                continue
            if lrefs <= right_ids and rrefs <= left_ids:
                lk.append(c.right)
                rk.append(c.left)
                continue
        residual.append(c)
    cond: Optional[Expression] = None
    for r in residual:
        cond = r if cond is None else And(cond, r)
    return lk, rk, cond
