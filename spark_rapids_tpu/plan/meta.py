"""Plan/expression metadata + tagging tree.

Reference parity: RapidsMeta.scala —
- `RapidsMeta.willNotWorkOnGpu(reason)` accumulation (:123) -> `will_not_work`
- `tagForGpu` recursion (:176-203) -> `tag_for_tpu`
- incompat/disabled-by-default gate logic (:185-200) -> `check_rule_gates`
- `convertIfNeeded` (:529-544) -> `convert_if_needed`
- explain tree printer (:245-283) -> `explain_string`
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Type

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.columnar.dtypes import is_supported_type
from spark_rapids_tpu.ops.base import Expression
from spark_rapids_tpu.exec.base import CpuExec, PhysicalExec


# ---------------------------------------------------------------------------
# Rules (reference: ReplacementRule / ExprRule / ExecRule,
# GpuOverrides.scala:82-130)
# ---------------------------------------------------------------------------
class ExprRule:
    def __init__(self, expr_cls: Type[Expression], desc: str,
                 incompat: Optional[str] = None,
                 disabled_by_default: bool = False,
                 tag_fn: Optional[Callable[["ExprMeta"], None]] = None):
        self.expr_cls = expr_cls
        self.desc = desc
        self.incompat = incompat
        self.disabled_by_default = disabled_by_default
        self.tag_fn = tag_fn
        # auto-generated per-op enable key (reference: ReplacementRule.confKey,
        # GpuOverrides.scala:125-130)
        self.conf_key = f"rapids.tpu.sql.expression.{expr_cls.__name__}"
        C.REGISTRY.register_dynamic(
            self.conf_key, f"Enable expression {expr_cls.__name__}: {desc}",
            None)


class ExecRule:
    def __init__(self, cpu_cls: Type[PhysicalExec], desc: str,
                 convert: Callable[[PhysicalExec, List[PhysicalExec]], PhysicalExec],
                 incompat: Optional[str] = None,
                 disabled_by_default: bool = False,
                 tag_fn: Optional[Callable[["ExecMeta"], None]] = None):
        self.cpu_cls = cpu_cls
        self.desc = desc
        self.convert = convert
        self.incompat = incompat
        self.disabled_by_default = disabled_by_default
        self.tag_fn = tag_fn
        self.conf_key = f"rapids.tpu.sql.exec.{cpu_cls.__name__}"
        C.REGISTRY.register_dynamic(
            self.conf_key, f"Enable exec {cpu_cls.__name__}: {desc}", None)


# ---------------------------------------------------------------------------
# Meta tree
# ---------------------------------------------------------------------------
class BaseMeta:
    def __init__(self, conf: C.TpuConf):
        self.conf = conf
        self._reasons: List[str] = []

    def will_not_work(self, reason: str) -> None:
        if reason not in self._reasons:
            self._reasons.append(reason)

    @property
    def can_replace(self) -> bool:
        return not self._reasons

    @property
    def reasons(self) -> List[str]:
        return list(self._reasons)


class ExprMeta(BaseMeta):
    def __init__(self, expr: Expression, conf: C.TpuConf,
                 rule: Optional[ExprRule]):
        super().__init__(conf)
        self.expr = expr
        self.rule = rule
        self.children = [wrap_expr(c, conf) for c in expr.children()]

    def tag_for_tpu(self) -> None:
        for c in self.children:
            c.tag_for_tpu()
        # type gate (reference: GpuOverrides.isSupportedType,
        # GpuOverrides.scala:383-395)
        try:
            dt = self.expr.data_type
        except Exception:
            dt = None
        if dt is not None and not is_supported_type(dt):
            self.will_not_work(f"expression produces unsupported type {dt}")
        if self.rule is None:
            self.will_not_work(
                f"no TPU rule for expression {type(self.expr).__name__}")
            return
        # conf gates (reference: RapidsMeta.scala:185-200)
        if not self.conf.is_operator_enabled(
                self.rule.conf_key,
                incompat=self.rule.incompat is not None,
                disabled_by_default=self.rule.disabled_by_default):
            why = self.rule.incompat or "disabled by default"
            self.will_not_work(
                f"expression {type(self.expr).__name__} is off "
                f"({why}; set {self.rule.conf_key}=true to enable)")
        if self.rule.tag_fn is not None:
            self.rule.tag_fn(self)
        # an expression can only go if all its children can
        for c in self.children:
            if not c.can_replace:
                self.will_not_work(
                    f"child expression {type(c.expr).__name__} cannot run on TPU")

    @property
    def subtree_can_replace(self) -> bool:
        return self.can_replace and all(
            c.subtree_can_replace for c in self.children)

    def all_reasons(self) -> List[str]:
        out = list(self._reasons)
        for c in self.children:
            out.extend(c.all_reasons())
        return out


class ExecMeta(BaseMeta):
    """Per-physical-node meta (reference: SparkPlanMeta)."""

    def __init__(self, plan: PhysicalExec, conf: C.TpuConf,
                 rule: Optional["ExecRule"],
                 expr_lookup: Callable[[Expression], Optional[ExprRule]]):
        super().__init__(conf)
        self.plan = plan
        self.rule = rule
        self.children = [wrap_plan(c, conf) for c in plan.children]
        self.expr_metas: List[ExprMeta] = [
            ExprMeta(e, conf, expr_lookup(e))
            for e in node_expressions(plan)
        ]

    def tag_for_tpu(self) -> None:
        for c in self.children:
            c.tag_for_tpu()
        for a in self.plan.output:
            if not is_supported_type(a.data_type):
                self.will_not_work(
                    f"output column {a.name} has unsupported type {a.data_type}")
        if self.rule is None:
            self.will_not_work(
                f"no TPU rule for exec {type(self.plan).__name__}")
        else:
            if not self.conf.is_operator_enabled(
                    self.rule.conf_key,
                    incompat=self.rule.incompat is not None,
                    disabled_by_default=self.rule.disabled_by_default):
                why = self.rule.incompat or "disabled by default"
                self.will_not_work(
                    f"exec {type(self.plan).__name__} is off "
                    f"({why}; set {self.rule.conf_key}=true to enable)")
            if self.rule.tag_fn is not None:
                self.rule.tag_fn(self)
        for em in self.expr_metas:
            em.tag_for_tpu()
            if not em.subtree_can_replace:
                self.will_not_work(
                    f"expression {type(em.expr).__name__} cannot run on TPU: "
                    + "; ".join(em.all_reasons()[:3]))

    def convert_if_needed(self) -> PhysicalExec:
        """Reference: RapidsMeta.convertIfNeeded (:529-544)."""
        new_children = [c.convert_if_needed() for c in self.children]
        if self.can_replace and self.rule is not None:
            return self.rule.convert(self.plan, new_children)
        if any(a is not b for a, b in zip(new_children, self.plan.children)):
            return self.plan.with_children(new_children)
        return self.plan

    # -- explain (reference: RapidsMeta.scala:245-283) ------------------------
    def explain_string(self, indent: int = 0, all_nodes: bool = True) -> str:
        marker = "*" if self.can_replace else "!"
        line = "  " * indent + f"{marker} {type(self.plan).__name__}"
        if self._reasons:
            line += " <- " + "; ".join(self._reasons)
        lines = [line] if (all_nodes or self._reasons) else []
        for c in self.children:
            sub = c.explain_string(indent + 1, all_nodes)
            if sub:
                lines.append(sub)
        return "\n".join(lines)


def explain_string(plan: PhysicalExec, indent: int = 0,
                   annotate: Optional[Callable[[PhysicalExec], str]] = None
                   ) -> str:
    """Render a FINAL physical plan with Spark-style whole-stage markers:
    every operator belonging to fused stage N prints as `*(N) Op` under its
    `TpuFusedStage(N)` node (reference: WholeStageCodegen's `*(N)` EXPLAIN
    prefix). Non-member nodes print bare.

    `annotate(node) -> suffix` appends a per-node suffix line-fragment —
    EXPLAIN ANALYZE (obs/analyze.py) uses it to print measured metrics
    beside each operator without duplicating this tree layout."""
    from spark_rapids_tpu.exec.fused import TpuFusedStageExec

    lines: List[str] = []

    def suffix(node: PhysicalExec) -> str:
        return annotate(node) if annotate is not None else ""

    def walk(node: PhysicalExec, depth: int, stage: Optional[int],
             remaining: int) -> None:
        if isinstance(node, TpuFusedStageExec):
            lines.append("  " * depth + node.node_name() + suffix(node))
            walk(node.children[0], depth + 1, node.stage_id, node.n_ops)
            return
        marker = f"*({stage}) " if stage is not None and remaining > 0 \
            else ""
        lines.append("  " * depth + marker + node.node_name()
                     + suffix(node))
        in_stage = stage is not None and remaining > 1
        for c in node.children:
            walk(c, depth + 1, stage if in_stage else None,
                 remaining - 1 if in_stage else 0)

    walk(plan, indent, None, 0)
    return "\n".join(lines)


# wiring set by overrides.py at import time (mutual recursion breaker)
_WRAP_PLAN: Optional[Callable] = None
_WRAP_EXPR: Optional[Callable] = None
_NODE_EXPRESSIONS: Optional[Callable] = None


def wrap_plan(plan: PhysicalExec, conf: C.TpuConf) -> ExecMeta:
    return _WRAP_PLAN(plan, conf)


def wrap_expr(expr: Expression, conf: C.TpuConf) -> ExprMeta:
    return _WRAP_EXPR(expr, conf)


def node_expressions(plan: PhysicalExec) -> List[Expression]:
    return _NODE_EXPRESSIONS(plan)
