"""TpuOverrides: the CPU->TPU plan rewrite driver + rule registry.

Reference parity: GpuOverrides.scala —
- rule registry, one ReplacementRule per CPU op (:461-1766) -> EXPR_RULES /
  EXEC_RULES below (feature modules register more at import time).
- `GpuOverrides.apply`: wrap plan -> tagForGpu -> explain -> convertIfNeeded
  (:1769-1826) -> `TpuOverrides.apply`.
- incompat taxonomy: ops whose TPU results differ in corner cases are tagged
  with a reason and gated behind rapids.tpu.sql.incompatibleOps.enabled or the
  per-op key (reference: ReplacementRule.incompat, GpuOverrides.scala:82-95).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type

import jax

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.columnar.batch import device_float64_supported
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec import basic as B
from spark_rapids_tpu.exec.base import PhysicalExec
from spark_rapids_tpu.ops import arithmetic as AR
from spark_rapids_tpu.ops import bitwise as BW
from spark_rapids_tpu.ops import datetimeops as DT
from spark_rapids_tpu.ops import mathx as MX
from spark_rapids_tpu.ops import misc as MISC
from spark_rapids_tpu.ops import nulls as N
from spark_rapids_tpu.ops import predicates as P
from spark_rapids_tpu.ops import stringops as S
from spark_rapids_tpu.ops import aggregates as AGG
from spark_rapids_tpu.ops.base import (
    Alias,
    AttributeReference,
    BoundReference,
    Expression,
    SortOrder,
)
from spark_rapids_tpu.ops.cast import Cast
from spark_rapids_tpu.ops.conditional import CaseWhen, If
from spark_rapids_tpu.ops.literals import Literal
from spark_rapids_tpu.plan import meta as MT
from spark_rapids_tpu.plan.meta import ExecMeta, ExecRule, ExprMeta, ExprRule

EXPR_RULES: Dict[Type[Expression], ExprRule] = {}
EXEC_RULES: Dict[Type[PhysicalExec], ExecRule] = {}


def register_expr(expr_cls, desc, incompat=None, disabled_by_default=False,
                  tag_fn=None):
    rule = ExprRule(expr_cls, desc, incompat, disabled_by_default, tag_fn)
    EXPR_RULES[expr_cls] = rule
    return rule


def register_exec(cpu_cls, desc, convert, incompat=None,
                  disabled_by_default=False, tag_fn=None):
    rule = ExecRule(cpu_cls, desc, convert, incompat, disabled_by_default,
                    tag_fn)
    EXEC_RULES[cpu_cls] = rule
    return rule


# ---------------------------------------------------------------------------
# Incompat tag helpers
# ---------------------------------------------------------------------------
def _tag_f64_on_tpu(m: ExprMeta) -> None:
    """DOUBLE math runs in f32 on TPU hardware (no f64 units); flag incompat
    unless the session opted in (the reference's float-corner-case taxonomy)."""
    try:
        dt = m.expr.data_type
    except Exception:
        return
    def _dt(c):
        try:
            return c.data_type
        except Exception:
            return None

    involves_f64 = dt is DataType.FLOAT64 or any(
        _dt(c) is DataType.FLOAT64 for c in m.expr.children())
    if involves_f64 and not device_float64_supported():
        if not m.conf.get(C.INCOMPATIBLE_OPS) and \
                m.conf.get_key(m.rule.conf_key) is None:
            m.will_not_work(
                "DOUBLE is computed as float32 on TPU (no f64 hardware); "
                "set rapids.tpu.sql.incompatibleOps.enabled=true to accept")


# ---------------------------------------------------------------------------
# Expression rules (reference registry: GpuOverrides.scala:461-1487)
# ---------------------------------------------------------------------------
def _register_expr_rules():
    r = register_expr
    # structural
    r(Alias, "name a result")
    r(AttributeReference, "reference an input column")
    r(BoundReference, "ordinal input reference")
    r(Literal, "literal value")
    r(Cast, "cast between types", tag_fn=_tag_cast)
    # arithmetic
    for cls in (AR.Add, AR.Subtract, AR.Multiply, AR.Divide,
                AR.IntegralDivide, AR.Remainder, AR.Pmod, AR.UnaryMinus,
                AR.UnaryPositive, AR.Abs, AR.Signum):
        r(cls, f"arithmetic {cls.__name__}", tag_fn=_tag_f64_on_tpu)
    # predicates / logic
    for cls in (P.EqualTo, P.LessThan, P.LessThanOrEqual, P.GreaterThan,
                P.GreaterThanOrEqual, P.EqualNullSafe, P.And, P.Or, P.Not,
                P.In):
        r(cls, f"predicate {cls.__name__}")
    # math (transcendental results can differ in ulps from libm; the reference
    # tags several of these incompat for the same reason)
    for cls in (MX.Sin, MX.Cos, MX.Tan, MX.Asin, MX.Acos, MX.Atan, MX.Sinh,
                MX.Cosh, MX.Tanh, MX.Asinh, MX.Acosh, MX.Atanh, MX.Cot,
                MX.Exp, MX.Expm1, MX.Log, MX.Log1p,
                MX.Log2, MX.Log10, MX.Sqrt, MX.Cbrt, MX.Pow, MX.Atan2,
                MX.Logarithm):
        r(cls, f"math {cls.__name__}",
          incompat="floating point results may differ in ulps from the CPU")
    for cls in (MX.Rint, MX.Floor, MX.Ceil, MX.ToDegrees, MX.ToRadians):
        r(cls, f"math {cls.__name__}", tag_fn=_tag_f64_on_tpu)
    r(MX.NormalizeNaNAndZero, "normalize -0.0 and NaN for float keys")
    # bitwise
    for cls in (BW.BitwiseAnd, BW.BitwiseOr, BW.BitwiseXor, BW.BitwiseNot,
                BW.ShiftLeft, BW.ShiftRight, BW.ShiftRightUnsigned):
        r(cls, f"bitwise {cls.__name__}")
    # nulls / conditional
    for cls in (N.IsNull, N.IsNotNull, N.IsNan, N.NaNvl, N.Coalesce,
                N.AtLeastNNonNulls):
        r(cls, f"null-handling {cls.__name__}")
    r(If, "if/else")
    r(CaseWhen, "case when")
    # strings
    for cls in (S.Length, S.Substring, S.Concat,
                S.StartsWith, S.EndsWith, S.Contains, S.Like, S.StringTrim,
                S.StringTrimLeft, S.StringTrimRight, S.ConcatWs):
        r(cls, f"string {cls.__name__}")

    def _literal_value(e):
        from spark_rapids_tpu.ops.literals import Literal as Lit

        node = e
        while hasattr(node, "child") and not isinstance(node, Lit):
            node = node.child
        return node.value if isinstance(node, Lit) else None

    def _borderless_literal_tag(child_idx, what):
        """Shared device gate for needle-driven string kernels: the
        argument must be a literal, and length-1 or borderless (no proper
        border => matches cannot self-overlap, so byte-order occurrence
        ranks equal Java's one-position scan)."""
        def tag(m):
            from spark_rapids_tpu.columnar.strings import has_border

            v = _literal_value(m.expr.children()[child_idx])
            if not isinstance(v, str):
                m.will_not_work(f"{what} needs a literal string argument")
            elif len(v.encode("utf-8")) > 1 and has_border(v.encode("utf-8")):
                m.will_not_work(
                    f"device {what} requires a self-overlap-free string "
                    f"({v!r} can overlap itself)")
        return tag

    r(S.StringReplace, "string StringReplace",
      tag_fn=_borderless_literal_tag(1, "replace"))

    def _tag_regexp_replace(m):
        from spark_rapids_tpu.columnar.strings import has_border

        repl = _literal_value(m.expr.children()[2])
        if isinstance(repl, str) and ("$" in repl or "\\" in repl):
            # Java-style $N group refs / escapes in the replacement: the
            # device kernel inserts literally, so keep these on the CPU
            m.will_not_work(
                "regexp replacement with $-references or escapes runs on "
                "the CPU (device replacement is literal)")
        pat = _literal_value(m.expr.children()[1])
        if not isinstance(pat, str) or pat == "":
            m.will_not_work(
                "regexp_replace needs a non-empty literal pattern")
        elif not S.RegExpReplace.is_simple_pattern(pat):
            # reference: only literal (metacharacter-free) patterns run on
            # the accelerator, GpuOverrides.scala:1458-1468
            m.will_not_work(
                f"regexp pattern {pat!r} contains regex metacharacters; "
                "only literal patterns are supported on device")
        elif len(pat.encode("utf-8")) > 1 and has_border(pat.encode("utf-8")):
            m.will_not_work(
                f"device replace requires a self-overlap-free pattern "
                f"({pat!r} can overlap itself)")

    r(S.RegExpReplace, "string RegExpReplace (literal patterns)",
      tag_fn=_tag_regexp_replace)
    r(S.StringLocate, "string locate (scalar substring/start)")

    r(S.SubstringIndex, "string substring_index (scalar delim/count)",
      tag_fn=_borderless_literal_tag(1, "substring_index"))
    for cls in (S.Upper, S.Lower, S.InitCap):
        r(cls, f"string {cls.__name__}",
          incompat="device case conversion is ASCII-only; non-ASCII "
                   "characters pass through unchanged")
    # datetime
    for cls in (DT.Year, DT.Month, DT.DayOfMonth, DT.Hour, DT.Minute,
                DT.Second, DT.DateDiff, DT.DateAdd, DT.DateSub, DT.LastDay,
                DT.DayOfWeek, DT.WeekDay, DT.DayOfYear, DT.Quarter):
        r(cls, f"datetime {cls.__name__}")
    r(DT.UnixTimestamp, "parse/convert to unix seconds",
      incompat="range/overflow behavior differs slightly from CPU "
               "(reference: improvedTimeOps)")
    r(DT.ToUnixTimestamp, "parse/convert to unix seconds",
      incompat="range/overflow behavior differs slightly from CPU "
               "(reference: improvedTimeOps)")
    r(DT.FromUnixTime, "format unix seconds as string")
    # nondeterministic
    r(MISC.Rand, "uniform random",
      incompat="TPU RNG stream differs from CPU XORShiftRandom")
    r(MISC.MonotonicallyIncreasingID, "monotonically increasing id")
    r(MISC.SparkPartitionID, "partition id")
    r(MISC.InputFileName, "input file name")
    r(MISC.InputFileBlockStart, "input file block start")
    r(MISC.InputFileBlockLength, "input file block length")
    # aggregate functions
    for cls in (AGG.Min, AGG.Max, AGG.Sum, AGG.Count, AGG.Average,
                AGG.First, AGG.Last):
        r(cls, f"aggregate {cls.__name__}", tag_fn=_tag_agg)
    r(AGG.Percentile, "exact percentile (holistic sort-based aggregate)",
      tag_fn=_tag_agg)
    # window (reference registry: GpuWindowExpression/GpuRowNumber etc.,
    # GpuOverrides.scala window expression rules)
    from spark_rapids_tpu.ops import window as W

    r(W.WindowExpression, "function over a window spec",
      tag_fn=_tag_window_expr)
    for cls in (W.RowNumber, W.Rank, W.DenseRank, W.NTile):
        r(cls, f"window ranking {cls.__name__}")
    r(W.Lag, "value from a preceding row")
    r(W.Lead, "value from a following row")


def _tag_cast(m: ExprMeta) -> None:
    e: Cast = m.expr
    src = e.child.data_type
    dst = e.to_type
    if not Cast.device_supported(src, dst):
        # conf-gated directions with device kernels (the reference's
        # GpuCast per-direction compat gates, RapidsConf.scala:393-425):
        # float->string and string->float need real f64 lanes (their
        # shared shortest-decimal / parse arithmetic runs in f64);
        # string->timestamp is pure integer work.
        from spark_rapids_tpu.columnar.batch import device_float64_supported

        if src.is_floating and dst is DataType.STRING:
            if not m.conf.get(C.ENABLE_CAST_FLOAT_TO_STRING):
                m.will_not_work(
                    "cast float->STRING on device is disabled by default "
                    "(set rapids.tpu.sql.castFloatToString.enabled; output "
                    "follows this framework's shortest-round-trip "
                    "convention, not Java's)")
            elif not device_float64_supported():
                m.will_not_work(
                    "cast float->STRING device kernel needs an f64-capable "
                    "backend (shortest-decimal search runs in f64)")
            return
        if src is DataType.STRING and dst.is_floating:
            if not m.conf.get(C.ENABLE_CAST_STRING_TO_FLOAT):
                m.will_not_work(
                    "cast STRING->float on device is disabled by default "
                    "(set rapids.tpu.sql.castStringToFloat.enabled)")
            elif not device_float64_supported():
                m.will_not_work(
                    "cast STRING->float device kernel needs an f64-capable "
                    "backend")
            elif e.ansi:
                # the deferred ANSI error channel only drains at
                # project/filter boundaries; in any other position the
                # flag would be silently dropped — keep ANSI parses on
                # the CPU engine, which raises in place
                m.will_not_work("ANSI STRING->float cast runs on the CPU "
                                "engine (deferred device errors only "
                                "surface at project/filter boundaries)")
            return
        if src is DataType.STRING and dst is DataType.TIMESTAMP:
            if not m.conf.get(C.ENABLE_CAST_STRING_TO_TIMESTAMP):
                m.will_not_work(
                    "cast STRING->TIMESTAMP on device is disabled by "
                    "default (set "
                    "rapids.tpu.sql.castStringToTimestamp.enabled)")
            elif e.ansi:
                m.will_not_work("ANSI STRING->TIMESTAMP cast runs on the "
                                "CPU engine (deferred device errors only "
                                "surface at project/filter boundaries)")
            return
        # directions with no device kernel (string->int parse,
        # decimal->string formatting, ...) run on the CPU engine — the
        # reference likewise tags unsupported cast directions for fallback
        # (GpuCast.scala per-direction gates, RapidsConf.scala:393-425).
        m.will_not_work(
            f"cast {getattr(src, 'name', src)}->{getattr(dst, 'name', dst)} "
            "has no device kernel")
    _tag_f64_on_tpu(m)


def _tag_window_expr(m: ExprMeta) -> None:
    """Gate window shapes the device kernel does not cover yet (the kernel
    computes frames via segmented prefix scans, exec/window.py)."""
    from spark_rapids_tpu.ops import window as W

    w = m.expr
    f = w.function
    if getattr(f, "holistic", False):
        # holistic aggregates (percentile) have no windowed evaluation in
        # EITHER engine — reject at planning, not with a runtime crash
        m.will_not_work(
            f"{type(f).__name__} is not supported as a window function")
    frame = w.spec.frame
    if frame.frame_type == "range" and (
            frame.lower not in (W.UNBOUNDED, 0)
            or frame.upper not in (W.UNBOUNDED, 0)):
        # bounded range frames binary-search the single numeric ORDER BY
        # key in the sorted domain (exec/window.py:_frame_bounds;
        # reference: GpuWindowExpression.scala:457-683)
        ob = w.spec.order_by
        dt = ob[0].child.data_type if len(ob) == 1 else None
        ok = dt in (DataType.INT8, DataType.INT16, DataType.INT32,
                    DataType.INT64, DataType.DATE, DataType.TIMESTAMP)
        if not ok:
            # float keys are excluded on the device: f64 narrows to f32 on
            # TPU and even f32 bound arithmetic rounds differently from the
            # oracle's f64 — frame membership is discrete, so a boundary
            # round-off silently moves whole rows between frames
            m.will_not_work(
                "bounded range frames need exactly one integer/date/"
                "timestamp ORDER BY column on the device engine")
    input_child = f.children()[0] if f.children() else None
    if input_child is not None and \
            input_child.data_type is DataType.STRING:
        m.will_not_work(
            "window functions over STRING inputs run on the CPU engine "
            "(no device string gather in the window kernel yet)")
    if isinstance(f, W.NTile) and f.n <= 0:
        m.will_not_work("ntile(n) requires n > 0")


def _tag_agg(m: ExprMeta) -> None:
    e = m.expr
    if isinstance(e, (AGG.Sum, AGG.Average)) and \
            e.child.data_type.is_floating:
        if not m.conf.get(C.ENABLE_FLOAT_AGG):
            m.will_not_work(
                "float aggregation order differs from CPU; set "
                "rapids.tpu.sql.variableFloatAgg.enabled=true")
    if e.child.data_type is DataType.STRING and not isinstance(e, AGG.Count):
        from spark_rapids_tpu.ops.base import AttributeReference

        if isinstance(e, (AGG.Min, AGG.Max)) and \
                isinstance(e.child, AttributeReference):
            # device string min/max via chunked-u64 arg-extreme reduction
            # (rowkeys.segment_arg_extreme_string); computed string inputs
            # need a length bound unknown outside jit -> CPU
            pass
        else:
            m.will_not_work(
                "this aggregate over STRING inputs runs on the CPU engine "
                "(device string reductions cover min/max of plain columns "
                "and count)")
    _tag_f64_on_tpu(m)


# ---------------------------------------------------------------------------
# Exec rules (reference registry: GpuOverrides.scala:1622-1766)
# ---------------------------------------------------------------------------
def _register_exec_rules():
    register_exec(
        B.CpuProjectExec, "columnar projection",
        lambda cpu, ch: B.TpuProjectExec(cpu.project_list, ch[0]))
    register_exec(
        B.CpuFilterExec, "columnar filter",
        lambda cpu, ch: B.TpuFilterExec(cpu.condition, ch[0]))
    register_exec(
        B.CpuUnionExec, "union-all",
        lambda cpu, ch: B.TpuUnionExec(*ch))
    register_exec(
        B.CpuLocalLimitExec, "per-partition limit",
        lambda cpu, ch: B.TpuLocalLimitExec(cpu.limit, ch[0]))
    register_exec(
        B.CpuGlobalLimitExec, "global limit",
        lambda cpu, ch: B.TpuGlobalLimitExec(cpu.limit, ch[0]))
    _register_feature_exec_rules()


def _register_feature_exec_rules():
    from spark_rapids_tpu.exec import join as J
    from spark_rapids_tpu.exec.aggregate import (
        CpuHashAggregateExec,
        TpuHashAggregateExec,
    )
    from spark_rapids_tpu.exec.sort import CpuSortExec, TpuSortExec
    from spark_rapids_tpu.shuffle import exchange as X

    register_exec(
        CpuHashAggregateExec, "hash aggregate (groupby via sort+segment-reduce)",
        lambda cpu, ch: TpuHashAggregateExec(
            cpu.grouping, cpu.agg_exprs, cpu.mode, ch[0], cpu.specs))

    def _tag_sort(m: ExecMeta):
        from spark_rapids_tpu.ops.base import AttributeReference

        for o in m.plan.orders:
            if o.child.data_type is DataType.STRING and \
                    not isinstance(o.child, AttributeReference):
                # plain string columns sort on device via chunked u64 order
                # keys (rowkeys.string_order_proxy); computed string keys
                # would need the result's max length, unknown outside jit
                m.will_not_work(
                    "device ordering of computed string expressions is not "
                    "implemented (plain string columns sort on device)")

    register_exec(
        CpuSortExec, "multi-key stable sort",
        lambda cpu, ch: TpuSortExec(cpu.orders, ch[0]),
        tag_fn=_tag_sort)

    def _tag_exchange(m: ExecMeta):
        from spark_rapids_tpu.ops.base import AttributeReference

        p = m.plan.partitioning
        if isinstance(p, X.RangePartitioning):
            for o in p.orders:
                if o.child.data_type is DataType.STRING and \
                        not isinstance(o.child, AttributeReference):
                    m.will_not_work(
                        "device range partitioning on computed string "
                        "expressions is not implemented")

    register_exec(
        X.CpuShuffleExchangeExec, "columnar shuffle exchange",
        lambda cpu, ch: X.TpuShuffleExchangeExec(cpu.partitioning, ch[0],
                                                 cpu.allow_adaptive),
        tag_fn=_tag_exchange)

    def _convert_join(tpu_cls):
        return lambda cpu, ch: tpu_cls(
            cpu.left_keys, cpu.right_keys, cpu.join_type, cpu.condition,
            ch[0], ch[1])

    register_exec(
        J.CpuShuffledHashJoinExec, "shuffled hash equi-join",
        _convert_join(J.TpuShuffledHashJoinExec))
    register_exec(
        J.CpuBroadcastHashJoinExec, "broadcast hash equi-join",
        _convert_join(J.TpuBroadcastHashJoinExec))
    register_exec(
        J.CpuNestedLoopJoinExec, "cross/nested-loop join",
        _convert_join(J.TpuNestedLoopJoinExec))

    from spark_rapids_tpu.exec.expand import (
        CpuExpandExec,
        CpuGenerateExec,
        TpuExpandExec,
        TpuGenerateExec,
    )

    register_exec(
        CpuExpandExec, "grouping-sets expand (one projection list per set)",
        lambda cpu, ch: TpuExpandExec(cpu.projections, cpu.output_attrs,
                                      ch[0]))

    def _tag_generate(m) -> None:
        elem_t = m.plan.generator_output[-1].data_type
        if elem_t is DataType.STRING:
            m.will_not_work(
                "device explode of string elements is not implemented")

    register_exec(
        CpuGenerateExec, "explode/posexplode of a created array",
        lambda cpu, ch: TpuGenerateExec(
            cpu.include_pos, cpu.elem_exprs, cpu.generator_output, ch[0]),
        tag_fn=_tag_generate)

    from spark_rapids_tpu.exec.cache import (
        CpuCachedScanExec,
        TpuCachedScanExec,
    )

    register_exec(
        CpuCachedScanExec, "device-resident in-memory table cache",
        lambda cpu, ch: TpuCachedScanExec(cpu.logical_node, ch[0]))

    from spark_rapids_tpu.io.scan import CpuFileScanExec, TpuFileScanExec

    _FMT_READ_CONF = {
        "parquet": C.PARQUET_READ_ENABLED,
        "orc": C.ORC_READ_ENABLED,
        "csv": C.CSV_READ_ENABLED,
    }

    def _tag_scan(m: ExecMeta):
        entry = _FMT_READ_CONF.get(m.plan.fmt)
        if entry is not None and not m.conf.get(entry):
            m.will_not_work(
                f"{m.plan.fmt} reads are disabled (set {entry.key}=true)")
        for a in m.plan.output:
            if not MT.is_supported_type(a.data_type):
                m.will_not_work(f"column {a.name} has unsupported type "
                                f"{a.data_type}")

    register_exec(
        CpuFileScanExec, "columnar file scan (Arrow host decode + upload)",
        lambda cpu, ch: TpuFileScanExec(cpu.attrs, cpu.splits, cpu.fmt),
        tag_fn=_tag_scan)

    from spark_rapids_tpu.exec.window import CpuWindowExec, TpuWindowExec

    register_exec(
        CpuWindowExec, "window functions (one-sort segmented-scan kernel)",
        lambda cpu, ch: TpuWindowExec(cpu.window_exprs, ch[0]))


# ---------------------------------------------------------------------------
# Node-expression extraction (which expressions does a node evaluate?)
# ---------------------------------------------------------------------------
_NODE_EXPR_GETTERS: Dict[Type[PhysicalExec], callable] = {}


def node_expressions_of(cls):
    def deco(fn):
        _NODE_EXPR_GETTERS[cls] = fn
        return fn
    return deco


def _node_expressions(plan: PhysicalExec) -> List[Expression]:
    fn = _NODE_EXPR_GETTERS.get(type(plan))
    if fn is not None:
        return fn(plan)
    if isinstance(plan, (B.CpuProjectExec, B.TpuProjectExec)):
        return list(plan.project_list)
    if isinstance(plan, (B.CpuFilterExec, B.TpuFilterExec)):
        return [plan.condition]
    from spark_rapids_tpu.exec.aggregate import _HashAggregateBase
    from spark_rapids_tpu.exec.join import _JoinBase
    from spark_rapids_tpu.exec.sort import _SortBase
    from spark_rapids_tpu.shuffle.exchange import (
        HashPartitioning,
        RangePartitioning,
        _ExchangeBase,
    )

    if isinstance(plan, _HashAggregateBase):
        return list(plan.key_exprs) + list(plan.agg_exprs)
    if isinstance(plan, _SortBase):
        return [o.child for o in plan.orders]
    if isinstance(plan, _ExchangeBase):
        p = plan.partitioning
        if isinstance(p, HashPartitioning):
            return list(p.exprs)
        if isinstance(p, RangePartitioning):
            return [o.child for o in p.orders]
        return []
    if isinstance(plan, _JoinBase):
        out = list(plan.left_keys) + list(plan.right_keys)
        if plan.condition is not None:
            out.append(plan.condition)
        return out
    from spark_rapids_tpu.exec.window import _WindowBase

    if isinstance(plan, _WindowBase):
        return list(plan.window_exprs)
    return []


# ---------------------------------------------------------------------------
# wrap + apply (reference: GpuOverrides.apply, :1769-1826)
# ---------------------------------------------------------------------------
def _expr_rule_for(e: Expression) -> Optional[ExprRule]:
    return EXPR_RULES.get(type(e))


def _wrap_plan(plan: PhysicalExec, conf: C.TpuConf) -> ExecMeta:
    return ExecMeta(plan, conf, EXEC_RULES.get(type(plan)), _expr_rule_for)


def _wrap_expr(expr: Expression, conf: C.TpuConf) -> ExprMeta:
    return ExprMeta(expr, conf, _expr_rule_for(expr))


MT._WRAP_PLAN = _wrap_plan
MT._WRAP_EXPR = _wrap_expr
MT._NODE_EXPRESSIONS = _node_expressions


class TpuOverrides:
    """The pre-transition columnar rule (reference: ColumnarOverrideRules
    preColumnarTransitions = GpuOverrides(), Plugin.scala:37-40)."""

    @staticmethod
    def apply(cpu_plan: PhysicalExec, conf: C.TpuConf,
              explain_out: Optional[List[str]] = None) -> PhysicalExec:
        if not conf.sql_enabled:
            return cpu_plan
        wrapped = _wrap_plan(cpu_plan, conf)
        wrapped.tag_for_tpu()
        explain = conf.explain
        if explain != "NONE" or explain_out is not None:
            text = wrapped.explain_string(all_nodes=(explain == "ALL"))
            if explain_out is not None:
                explain_out.append(wrapped.explain_string(all_nodes=True))
            if explain != "NONE" and text:
                # tpulint: stdout-print -- the explain conf asks for console
                print(text)
        return wrapped.convert_if_needed()


_register_expr_rules()
_register_exec_rules()
