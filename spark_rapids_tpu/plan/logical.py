"""Logical plan nodes.

The reference rides Spark Catalyst for the logical layer and only rewrites
physical plans; a standalone framework needs its own (small) logical algebra.
The node set mirrors the operators the reference accelerates
(SURVEY.md section 2.6): scan/filter/project/agg/join/sort/window/expand/
generate/limit/union/repartition/write.
"""

from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.base import (
    Alias,
    AttributeReference,
    Expression,
    SortOrder,
    to_attribute,
)


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"
    CROSS = "cross"

    @staticmethod
    def parse(s: str) -> "JoinType":
        aliases = {
            "inner": JoinType.INNER,
            "left": JoinType.LEFT_OUTER, "leftouter": JoinType.LEFT_OUTER,
            "left_outer": JoinType.LEFT_OUTER,
            "right": JoinType.RIGHT_OUTER, "rightouter": JoinType.RIGHT_OUTER,
            "right_outer": JoinType.RIGHT_OUTER,
            "outer": JoinType.FULL_OUTER, "full": JoinType.FULL_OUTER,
            "fullouter": JoinType.FULL_OUTER, "full_outer": JoinType.FULL_OUTER,
            "semi": JoinType.LEFT_SEMI, "leftsemi": JoinType.LEFT_SEMI,
            "left_semi": JoinType.LEFT_SEMI,
            "anti": JoinType.LEFT_ANTI, "leftanti": JoinType.LEFT_ANTI,
            "left_anti": JoinType.LEFT_ANTI,
            "cross": JoinType.CROSS,
        }
        k = s.strip().lower().replace(" ", "")
        if k not in aliases:
            raise ValueError(f"unknown join type {s!r}")
        return aliases[k]


class LogicalPlan:
    def __init__(self, *children: "LogicalPlan"):
        self.children: Tuple[LogicalPlan, ...] = children

    @property
    def output(self) -> List[AttributeReference]:
        raise NotImplementedError(type(self).__name__)

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return type(self).__name__


class LocalRelation(LogicalPlan):
    """In-memory host data (host batches pre-split into partitions)."""

    def __init__(self, schema: List[AttributeReference], partitions):
        super().__init__()
        self.schema = schema
        self.partitions = partitions

    @property
    def output(self):
        return self.schema

    def describe(self):
        return f"LocalRelation[{', '.join(a.name for a in self.schema)}]"


class RangeRelation(LogicalPlan):
    def __init__(self, start: int, end: int, step: int, num_partitions: int):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self._attr = AttributeReference("id", DataType.INT64, False)

    @property
    def output(self):
        return [self._attr]


class FileScan(LogicalPlan):
    """v2-style file scan (reference: GpuBatchScanExec / Gpu*Scan)."""

    def __init__(self, fmt: str, paths: List[str],
                 schema: Optional[List[AttributeReference]],
                 options: Optional[Dict[str, Any]] = None,
                 files: Optional[List[str]] = None):
        super().__init__()
        self.fmt = fmt
        self.paths = paths
        self.schema = schema  # resolved lazily by the session if None
        self.options = dict(options or {})
        # file list already discovered during schema resolution (avoids a
        # second directory walk at planning time)
        self.files = files

    @property
    def output(self):
        assert self.schema is not None, "unresolved file scan"
        return self.schema

    def describe(self):
        return f"FileScan {self.fmt} {self.paths}"


class Project(LogicalPlan):
    def __init__(self, project_list: Sequence[Expression], child: LogicalPlan):
        super().__init__(child)
        self.project_list = list(project_list)

    @property
    def output(self):
        return [to_attribute(e) for e in self.project_list]

    def describe(self):
        return f"Project [{', '.join(map(repr, self.project_list))}]"


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        super().__init__(child)
        self.condition = condition

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"Filter ({self.condition!r})"


class Aggregate(LogicalPlan):
    """Group-by aggregate. agg_exprs are Alias(AggregateFunction | expr over
    grouping columns)."""

    def __init__(self, grouping: Sequence[Expression],
                 agg_exprs: Sequence[Expression], child: LogicalPlan):
        super().__init__(child)
        self.grouping = list(grouping)
        self.agg_exprs = list(agg_exprs)

    @property
    def output(self):
        return [to_attribute(e) for e in self.agg_exprs]

    def describe(self):
        return (f"Aggregate [{', '.join(map(repr, self.grouping))}] "
                f"[{', '.join(map(repr, self.agg_exprs))}]")


class Sort(LogicalPlan):
    def __init__(self, orders: Sequence[SortOrder], is_global: bool,
                 child: LogicalPlan):
        super().__init__(child)
        self.orders = list(orders)
        self.is_global = is_global

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        scope = "global" if self.is_global else "local"
        return f"Sort {scope} [{', '.join(map(repr, self.orders))}]"


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 join_type: JoinType,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition

    @property
    def output(self):
        left, right = self.children
        if self.join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            return left.output
        def nullable(attrs):
            return [AttributeReference(a.name, a.data_type, True, a.expr_id)
                    for a in attrs]
        if self.join_type is JoinType.LEFT_OUTER:
            return left.output + nullable(right.output)
        if self.join_type is JoinType.RIGHT_OUTER:
            return nullable(left.output) + right.output
        if self.join_type is JoinType.FULL_OUTER:
            return nullable(left.output) + nullable(right.output)
        return left.output + right.output

    def describe(self):
        return (f"Join {self.join_type.value} keys="
                f"{list(zip(self.left_keys, self.right_keys))} "
                f"cond={self.condition!r}")


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        super().__init__(child)
        self.n = n

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"Limit {self.n}"


class Union(LogicalPlan):
    def __init__(self, *children: LogicalPlan):
        super().__init__(*children)

    @property
    def output(self):
        return self.children[0].output


class Repartition(LogicalPlan):
    """Round-robin (no exprs) or hash (exprs) repartition; `coalesce_only`
    maps to partition coalescing without a shuffle."""

    def __init__(self, num_partitions: Optional[int],
                 partition_exprs: Sequence[Expression],
                 coalesce_only: bool, child: LogicalPlan):
        super().__init__(child)
        self.num_partitions = num_partitions
        self.partition_exprs = list(partition_exprs)
        self.coalesce_only = coalesce_only

    @property
    def output(self):
        return self.children[0].output


class Expand(LogicalPlan):
    """Multiple projection lists per input row (grouping sets;
    reference: GpuExpandExec)."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 output_attrs: List[AttributeReference], child: LogicalPlan):
        super().__init__(child)
        self.projections = [list(p) for p in projections]
        self.output_attrs = output_attrs

    @property
    def output(self):
        return self.output_attrs


class Generate(LogicalPlan):
    """Explode of an array-producing expression (reference: GpuGenerateExec).
    v1 scope: explode(array literal columns) + posexplode."""

    def __init__(self, generator: Expression, generator_output: List[AttributeReference],
                 outer: bool, child: LogicalPlan):
        super().__init__(child)
        self.generator = generator
        self.generator_output = generator_output
        self.outer = outer

    @property
    def output(self):
        return self.children[0].output + self.generator_output


class WindowOp(LogicalPlan):
    """Window expressions appended to child output (reference: GpuWindowExec)."""

    def __init__(self, window_exprs: Sequence[Expression], child: LogicalPlan):
        super().__init__(child)
        self.window_exprs = list(window_exprs)

    @property
    def output(self):
        return self.children[0].output + [to_attribute(e) for e in self.window_exprs]


class CacheRelation(LogicalPlan):
    """Marks the child as cached in memory (reference: InMemoryRelation,
    accelerated via HostColumnarToGpu / cache_test.py). The physical cache
    exec materializes the child once per engine placement and serves the
    stored batches afterwards."""

    def __init__(self, child: LogicalPlan):
        super().__init__(child)

    @property
    def output(self):
        return self.children[0].output


class WriteFile(LogicalPlan):
    """Write to files (reference: GpuInsertIntoHadoopFsRelationCommand +
    GpuParquetFileFormat/GpuOrcFileFormat)."""

    def __init__(self, fmt: str, path: str, mode: str,
                 options: Dict[str, Any],
                 partition_by: List[str], child: LogicalPlan):
        super().__init__(child)
        self.fmt = fmt
        self.path = path
        self.mode = mode
        self.options = dict(options)
        self.partition_by = list(partition_by)

    @property
    def output(self):
        return []

    def describe(self):
        return f"WriteFile {self.fmt} -> {self.path} mode={self.mode}"
