"""Cost-based placement analyzer (docs/placement.md).

A bottom-up abstract COST interpreter over the final physical plan — the
PR 3 mold (plan/resources.py) applied to the device-vs-host decision:
every operator is priced on the device (the fitted CostModel,
obs/calibrate.py) and on the host (the parallel host-side fit trained
from CPU-fallback history and `BENCH_*_cpu.json` artifacts), every
would-be boundary is priced at the fitted transfer coefficients
(bytes x upload/download ns/byte + a per-fence constant), and a dynamic
program over the plan tree picks the cheapest side per subtree:

    dev(n)  = dev_op(n)  + sum_c min(dev(c),  host(c) + up(c))
    host(n) = host_op(n) + sum_c min(host(c), dev(c)  + down(c))

The winning assignment is REALIZED, not just reported: host-side device
operators are swapped for their Cpu twins (the inverse of the
plan/overrides.py EXEC_RULES map), and the standard transition pass
re-inserts `HostToDeviceExec`/`DeviceToHostExec` at exactly the chosen
boundaries — so a mixed plan flows through the same verifier
(plan/verify.py placement rules), resource analyzer, and executor as an
all-device one.

Cold-start contract (`rapids.tpu.sql.placement.minSamples`): in `auto`
mode an operator class leaves the device only when the decision is
calibrated on BOTH sides — the host model carries >= minSamples for the
class, and the device side is fitted either per-class or at the stage
granularity the device actually executes (SPMD/fusion rolls member
spans into the stage class, so a member class the device model has
never seen is priced by its fitted stage class). Below that the class
is pinned to the TPU, and with no fitted model at all the pass is an
exact no-op (today's all-device behavior). SPMD chains are all-or-nothing — a
`TpuSpmdStageExec` either stays a single device program or its ORIGINAL
subtree (children[0]) is re-placed host-side wholesale — so no chain
ever straddles a boundary. Encoded-claiming device scans are
device-pinned in auto mode (their dictionary claims are meaningless to
a host scan).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.exec import base as B
from spark_rapids_tpu.exec import basic as XB
from spark_rapids_tpu.exec import cache as XC
from spark_rapids_tpu.exec import expand as XE
from spark_rapids_tpu.exec import join as XJ
from spark_rapids_tpu.exec import sort as XS
from spark_rapids_tpu.exec import window as XW
from spark_rapids_tpu.exec.aggregate import (
    CpuHashAggregateExec,
    TpuHashAggregateExec,
)
from spark_rapids_tpu.exec.fused import TpuFusedStageExec
from spark_rapids_tpu.exec.transitions import (
    CpuCoalesceBatchesExec,
    DeviceToHostExec,
    HostToDeviceExec,
    TpuCoalesceBatchesExec,
)
from spark_rapids_tpu.io.scan import CpuFileScanExec, TpuFileScanExec
from spark_rapids_tpu.plan.spmd import TpuSpmdStageExec
from spark_rapids_tpu.plan.transition_overrides import (
    _insert_transitions,
    _optimize_transitions,
)
from spark_rapids_tpu.shuffle.exchange import (
    CpuShuffleExchangeExec,
    TpuShuffleExchangeExec,
)

_INF = float("inf")

# nodes the DP looks THROUGH: their cost is their child's, and the
# realization pass rebuilds/re-inserts them on whichever side the child
# landed (transitions are dropped and re-inserted by the standard pass)
_TRANSITIONS = (HostToDeviceExec, DeviceToHostExec)
_COALESCES = (TpuCoalesceBatchesExec, CpuCoalesceBatchesExec)


def _host_equiv(node: B.PhysicalExec,
                kids: Tuple[B.PhysicalExec, ...]) -> Optional[B.PhysicalExec]:
    """The Cpu twin of one device operator over already-realized
    children — the inverse of the plan/overrides.py EXEC_RULES map.
    None when the node has no host form (AQE stage atoms, transitions)."""
    if isinstance(node, XB.TpuProjectExec):
        return XB.CpuProjectExec(node.project_list, kids[0])
    if isinstance(node, XB.TpuFilterExec):
        return XB.CpuFilterExec(node.condition, kids[0])
    if isinstance(node, XB.TpuUnionExec):
        return XB.CpuUnionExec(*kids)
    if isinstance(node, XB.TpuLocalLimitExec):
        return XB.CpuLocalLimitExec(node.limit, kids[0])
    if isinstance(node, XB.TpuGlobalLimitExec):
        return XB.CpuGlobalLimitExec(node.limit, kids[0])
    if isinstance(node, TpuHashAggregateExec):
        return CpuHashAggregateExec(node.grouping, node.agg_exprs,
                                    node.mode, kids[0], node.specs)
    if isinstance(node, XS.TpuSortExec):
        return XS.CpuSortExec(node.orders, kids[0])
    if isinstance(node, XW.TpuWindowExec):
        return XW.CpuWindowExec(node.window_exprs, kids[0])
    if isinstance(node, TpuShuffleExchangeExec):
        return CpuShuffleExchangeExec(node.partitioning, kids[0],
                                      node.allow_adaptive)
    if isinstance(node, XJ.TpuShuffledHashJoinExec):
        return XJ.CpuShuffledHashJoinExec(
            node.left_keys, node.right_keys, node.join_type,
            node.condition, kids[0], kids[1])
    if isinstance(node, XJ.TpuBroadcastHashJoinExec):
        return XJ.CpuBroadcastHashJoinExec(
            node.left_keys, node.right_keys, node.join_type,
            node.condition, kids[0], kids[1])
    if isinstance(node, XJ.TpuNestedLoopJoinExec):
        return XJ.CpuNestedLoopJoinExec(
            node.left_keys, node.right_keys, node.join_type,
            node.condition, kids[0], kids[1])
    if isinstance(node, XE.TpuExpandExec):
        return XE.CpuExpandExec(node.projections, node.output_attrs,
                                kids[0])
    if isinstance(node, XE.TpuGenerateExec):
        return XE.CpuGenerateExec(node.include_pos, node.elem_exprs,
                                  node.generator_output, kids[0])
    if isinstance(node, XC.TpuCachedScanExec):
        return XC.CpuCachedScanExec(node.logical_node, kids[0])
    if isinstance(node, TpuFileScanExec):
        # a FRESH scan: any encoded-dictionary claims on the device scan
        # describe device decode output and must not survive conversion
        return CpuFileScanExec(node.attrs, node.splits, node.fmt)
    if isinstance(node, TpuFusedStageExec):
        # unfuse onto the host: rebuild the member chain bottom-up over
        # the realized stage input (members[0] is the chain top)
        cur = kids[0]
        for m in reversed(node.members):
            cur = _host_equiv(m, (cur,))
            if cur is None:
                return None
        return cur
    return None


# a host-placed shuffle below this many estimated rows collapses to one
# partition: the device plan's fan-out (conf shuffle partitions) buys
# nothing on the host interpreter and costs a scheduler round-trip per
# post-shuffle partition — exactly the toy-scale tax placement exists
# to remove
_HOST_COALESCE_ROWS = 1 << 16


def _coalesce_host_exchange(twin: "CpuShuffleExchangeExec",
                            rows_hi: float) -> "CpuShuffleExchangeExec":
    """Partition count is not semantic (collect concatenates partitions
    and oracle comparisons ignore order), so only the fan-out changes;
    unestimated (rows_hi <= 0) inputs keep the planned width."""
    from spark_rapids_tpu.shuffle.exchange import (HashPartitioning,
                                                   RoundRobinPartitioning)

    part = twin.partitioning
    if rows_hi <= 0 or rows_hi > _HOST_COALESCE_ROWS:
        return twin
    if isinstance(part, HashPartitioning) and part.num_partitions > 1:
        new = HashPartitioning(part.exprs, 1)
    elif isinstance(part, RoundRobinPartitioning) and \
            part.num_partitions > 1:
        new = RoundRobinPartitioning(1)
    else:
        return twin
    return CpuShuffleExchangeExec(new, twin.children[0],
                                  twin.allow_adaptive)


def _host_convertible(node: B.PhysicalExec) -> bool:
    if isinstance(node, TpuFusedStageExec):
        return all(_host_convertible(m) for m in node.members)
    probe = (XB.TpuProjectExec, XB.TpuFilterExec, XB.TpuUnionExec,
             XB.TpuLocalLimitExec, XB.TpuGlobalLimitExec,
             TpuHashAggregateExec, XS.TpuSortExec, XW.TpuWindowExec,
             TpuShuffleExchangeExec, XJ.TpuShuffledHashJoinExec,
             XJ.TpuBroadcastHashJoinExec, XJ.TpuNestedLoopJoinExec,
             XE.TpuExpandExec, XE.TpuGenerateExec, XC.TpuCachedScanExec,
             TpuFileScanExec)
    return isinstance(node, probe)


def _is_aqe_atom(node: B.PhysicalExec) -> bool:
    """Materialized AQE artifacts: their data already lives where it
    lives — the DP treats them as zero-cost device leaves and never
    descends (a host parent pays the download at the edge)."""
    return type(node).__name__ in ("TpuQueryStageExec",
                                   "TpuStageReaderExec")


class PlacementDecision:
    """One operator's price comparison + chosen side."""

    __slots__ = ("name", "cls", "device_ns", "host_ns", "side", "why")

    def __init__(self, name: str, cls: str, device_ns: float,
                 host_ns: float, side: str, why: str = ""):
        self.name = name
        self.cls = cls
        self.device_ns = device_ns
        self.host_ns = host_ns
        self.side = side
        self.why = why


class PlacementReport:
    """The analyzer's verdict for one final physical plan: per-operator
    prices, the chosen assignment, and the predicted cost of the road
    not taken (the post-hoc `placementRegret` baseline)."""

    def __init__(self, mode: str):
        self.mode = mode
        self.changed = False
        self.reason: Optional[str] = None
        self.decisions: List[PlacementDecision] = []
        self.host_ops = 0
        self.device_ops = 0
        self.boundaries = 0
        # predicted ns of the EMITTED plan and of the all-device
        # alternative; wall > alt_ns after choosing to move work means
        # the move was regretted (obs/history.py scores it)
        self.predicted_ns: Optional[float] = None
        self.alt_device_ns: Optional[float] = None
        self.transfer: Optional[dict] = None

    def render(self) -> str:
        head = f"placement: mode={self.mode}"
        if self.reason:
            return f"{head} — {self.reason}"
        lines = [head + f", {self.device_ops} device / "
                 f"{self.host_ops} host op(s), "
                 f"{self.boundaries} boundary transition(s)"]
        if self.predicted_ns is not None and \
                self.alt_device_ns is not None:
            lines.append(
                f"predicted {self.predicted_ns / 1e6:.3f} ms placed vs "
                f"{self.alt_device_ns / 1e6:.3f} ms all-device")
        for d in self.decisions:
            dev = "inf" if d.device_ns == _INF \
                else f"{d.device_ns / 1e6:.3f}ms"
            host = "inf" if d.host_ns == _INF \
                else f"{d.host_ns / 1e6:.3f}ms"
            note = f" ({d.why})" if d.why else ""
            lines.append(f"{d.name}: device={dev} host={host} "
                         f"-> {d.side}{note}")
        return "\n".join(lines)

    def to_payload(self) -> dict:
        """Flight-recorder form (obs/history.py attaches regret)."""
        out = {
            "mode": self.mode,
            "changed": self.changed,
            "hostOps": self.host_ops,
            "deviceOps": self.device_ops,
            "boundaries": self.boundaries,
        }
        if self.reason:
            out["reason"] = self.reason
        if self.predicted_ns is not None and self.predicted_ns != _INF:
            out["predictedNs"] = round(self.predicted_ns, 1)
        # the regret baseline is the predicted cost of what we did NOT
        # emit: all-device when we moved work, absent otherwise
        if self.changed and self.alt_device_ns is not None and \
                self.alt_device_ns != _INF:
            out["altNs"] = round(self.alt_device_ns, 1)
        if self.decisions:
            out["decisions"] = [
                {"name": d.name, "cls": d.cls, "side": d.side,
                 "deviceNs": None if d.device_ns == _INF
                 else round(d.device_ns, 1),
                 "hostNs": None if d.host_ns == _INF
                 else round(d.host_ns, 1)}
                for d in self.decisions[:64]]
        return out


class _Coster:
    """Per-node device/host operator prices from the two fitted models
    and the resource analyzer's estimates."""

    def __init__(self, est_map, dev_model, host_model, min_samples: int,
                 flat_ns: float, pin_host: Set[str],
                 lenient: bool = False):
        self.est_map = est_map
        self.dev_model = dev_model
        self.host_model = host_model
        self.min_samples = max(1, int(min_samples))
        self.flat_ns = max(0.0, flat_ns)
        self.pin_host = pin_host
        # forced-host mode prices cold classes at zero instead of INF:
        # the mode exists to RUN host-side (and to train the host fit),
        # so an unfitted class must not veto it
        self.lenient = lenient
        # >0 while pricing the host alternative of a stage atom whose
        # DEVICE price is its own calibrated stage class: the per-class
        # device-calibration gate in host_op_named is moot there — the
        # device never executes the members individually
        self.stage_depth = 0
        self._dispatch_ns_memo: Optional[float] = None

    def dev_calibrated(self, node) -> bool:
        """True when the device price of this stage atom comes from its
        own fitted class (the granularity the device executes)."""
        from spark_rapids_tpu.obs import calibrate as CAL

        if self.dev_model is None:
            return False
        cls = CAL.classify(node.node_name())
        return self.dev_model.coeffs_for(cls, self.min_samples) \
            is not None

    @staticmethod
    def _hi(iv) -> float:
        lo = float(iv.lo)
        hi = float(iv.hi)
        return lo if hi == _INF else hi

    def _rows(self, node) -> float:
        est = self.est_map.get(id(node))
        return self._hi(est.rows) if est is not None else 0.0

    def bytes_of(self, node) -> float:
        cur = node
        while True:
            est = self.est_map.get(id(cur))
            if est is not None:
                return float(est.resident_bytes)
            if cur.children and isinstance(
                    cur, _TRANSITIONS + _COALESCES +
                    (XB.CoalescePartitionsExec,)):
                cur = cur.children[0]
                continue
            # no estimate (host-native leaves like HostScanExec never
            # enter the analyzer's resident set): price the boundary at
            # the fence constant alone — same as a cpu-placed node's
            # zero resident bytes, and never an INF that would forbid
            # every boundary over an unestimated subtree
            return 0.0

    def dev_op(self, node) -> float:
        from spark_rapids_tpu.obs import calibrate as CAL

        if self.pin_host:
            # the failure re-placement path: a pinned class just faulted
            # on the device, so no fitted price makes it attractive —
            # and a stage FUSING a pinned member is poisoned wholesale
            if CAL.classify(node.node_name()) in self.pin_host:
                return _INF
            if isinstance(node, TpuFusedStageExec) and any(
                    CAL.classify(m.node_name()) in self.pin_host
                    for m in node.members):
                return _INF
        est = self.est_map.get(id(node))
        if est is None:
            return self.flat_ns
        if self.dev_model is not None:
            # the same minSamples contract as the host side: a class
            # with fewer samples (one stray bench record) must not
            # price a whole stage
            pred = self.dev_model.predict_node_ns(
                est.name, est.dispatches, est.rows, self.min_samples)
            if pred is not None:
                return pred[0] if pred[1] == _INF else pred[1]
        return self._hi(est.dispatches) * self._dispatch_ns()

    def _dispatch_ns(self) -> float:
        """Per-dispatch price for a class the device model never saw:
        the fitted model's own median ns_per_dispatch (launch + fence
        overhead is roughly class-independent, and a measured scale
        beats the conf constant), falling back to the conf constant
        only when nothing is fitted."""
        if self._dispatch_ns_memo is None:
            fitted = []
            if self.dev_model is not None:
                fitted = sorted(
                    c.ns_per_dispatch
                    for c in self.dev_model.coeffs.values()
                    if c.samples >= self.min_samples and
                    c.ns_per_dispatch > 0)
            self._dispatch_ns_memo = \
                fitted[len(fitted) // 2] if fitted else self.flat_ns
        return self._dispatch_ns_memo

    def host_op(self, node) -> float:
        """Host price of one operator, or INF when the cold-start
        contract pins its class to the device."""
        return self.host_op_named(node.node_name(), self._rows(node))

    def fused_host_op(self, node: TpuFusedStageExec) -> float:
        """An unfused host chain prices as the sum of its members'
        class predictions at the stage's row estimate."""
        rows = self._rows(node)
        total = 0.0
        for m in node.members:
            c = self.host_op_named(m.node_name(), rows)
            if c == _INF:
                return _INF
            total += c
        return total

    def host_op_named(self, name: str, rows: float) -> float:
        from spark_rapids_tpu.obs import calibrate as CAL

        cls = CAL.classify(name)
        if cls in self.pin_host or self.lenient:
            hc = self.host_model.coeffs_for(cls, 1) \
                if self.host_model is not None else None
            return hc.predict_ns(0.0, rows) if hc is not None else 0.0
        if self.dev_model is None or self.host_model is None:
            return _INF
        hc = self.host_model.coeffs_for(cls, self.min_samples)
        if hc is None:
            return _INF
        # the device side of the comparison must be calibrated too —
        # per-class when the device model has seen the class, or at the
        # stage granularity the device actually executes (SPMD/fusion
        # rolls member spans into the stage class; under stage_depth
        # the enclosing atom's fitted stage class IS the device price,
        # so an under-sampled member class must not veto the move)
        if not self.stage_depth and cls in self.dev_model.coeffs and \
                self.dev_model.coeffs_for(cls, self.min_samples) is None:
            return _INF
        return hc.predict_ns(0.0, rows)


def place_plan(plan: B.PhysicalExec, conf,
               device_manager=None, measured_stats=None,
               pin_host_classes: Optional[Set[str]] = None,
               forced_mode: Optional[str] = None):
    """Price + (maybe) re-place one FINAL physical plan. Returns
    (placed_plan, PlacementReport); the plan object is the ORIGINAL
    when the DP keeps everything on the device.

    `pin_host_classes` is the failure re-placement hook (session
    `_degrade_device_failure`): those operator classes price at
    device=INF so the DP moves exactly the faulting subtree host-side.
    `measured_stats` flows to the resource analyzer (the AQE re-place
    rule passes the stages' measured MapOutputStats)."""
    from spark_rapids_tpu.obs import calibrate as CAL
    from spark_rapids_tpu.plan import resources as R

    mode = forced_mode or conf.get(C.PLACEMENT_MODE)
    report = PlacementReport(mode)
    pin_host = set(pin_host_classes or ())

    if mode == "device":
        report.reason = "forced all-device"
        return plan, report

    dev_model = CAL.active_model()
    host_model = CAL.active_host_model()
    if mode == "auto" and not pin_host and \
            (dev_model is None or host_model is None):
        missing = "device" if dev_model is None else "host"
        report.reason = f"cold start: no fitted {missing} model " \
            f"(all-device)"
        return plan, report

    # price every node off the analyzer's estimates (measured stats win
    # over static bounds when the AQE loop supplies them)
    try:
        res = R.analyze_plan(plan, conf, device_manager=device_manager,
                             measured_stats=measured_stats)
        est_map = {est.node_id: est for est in res.nodes}
    except Exception:  # noqa: BLE001 - placement is best-effort
        est_map = {}

    flat_ns = max(0.0, float(
        conf.get(C.DEADLINE_COST_PER_DISPATCH_MS))) * 1e6
    force_host = mode == "host"
    # forced-host mode AND failure re-placement price the host leniently
    # (cold classes at their best guess instead of INF): both exist to
    # GET OFF the device, not to win a calibrated comparison
    coster = _Coster(est_map, dev_model, host_model,
                     conf.get(C.PLACEMENT_MIN_SAMPLES), flat_ns,
                     pin_host, lenient=force_host or bool(pin_host))
    tc = CAL.transfer_coeffs(dev_model)
    report.transfer = tc.as_dict()

    # -- the DP -------------------------------------------------------------
    memo: Dict[int, Tuple[float, float]] = {}

    def up(node) -> float:
        b = coster.bytes_of(node)
        return _INF if b == _INF else tc.upload_ns(b)

    def down(node) -> float:
        b = coster.bytes_of(node)
        return _INF if b == _INF else tc.download_ns(b)

    def costs(node) -> Tuple[float, float]:
        """(dev, host): cheapest cost of computing this subtree with
        its OUTPUT resident on the device / on the host."""
        got = memo.get(id(node))
        if got is not None:
            return got
        if isinstance(node, _TRANSITIONS) or \
                isinstance(node, _COALESCES) or \
                isinstance(node, XB.CoalescePartitionsExec):
            out = costs(node.children[0])
        elif _is_aqe_atom(node):
            out = (0.0, _INF)
        elif isinstance(node, TpuSpmdStageExec):
            # all-or-nothing: one device program, or the original
            # subtree re-placed host wholesale (never straddled). The
            # device price is the stage's OWN class, so the host
            # alternative is priced with the per-member device gate
            # relaxed (stage_depth)
            relax = coster.dev_calibrated(node)
            if relax:
                coster.stage_depth += 1
            try:
                host = costs(node.children[0])[1]
            finally:
                if relax:
                    coster.stage_depth -= 1
            dev = _INF if force_host else coster.dev_op(node)
            if pin_host and dev != _INF:
                from spark_rapids_tpu.obs import calibrate as CAL2

                # a single-program stage chaining a pinned (faulted)
                # operator class is poisoned wholesale
                if node.children[0].collect_nodes(
                        lambda n: CAL2.classify(n.node_name())
                        in pin_host):
                    dev = _INF
            out = (dev, host)
        elif isinstance(node, TpuFusedStageExec):
            # the fused node WRAPS its member chain (children[0] is the
            # chain top); price the stage as one operator over the node
            # BELOW the chain so the members are never double-counted
            inp = node.input_node
            cd, ch = costs(inp)
            kid_dev = min(cd, ch + up(inp))
            kid_host = min(ch, cd + down(inp))
            relax = coster.dev_calibrated(node)
            if relax:
                coster.stage_depth += 1
            try:
                host_self = coster.fused_host_op(node)
            finally:
                if relax:
                    coster.stage_depth -= 1
            dev = coster.dev_op(node) + kid_dev
            host = (host_self + kid_host) if host_self != _INF else _INF
            if force_host and host != _INF:
                dev = _INF
            out = (dev, host)
        elif getattr(node, "placement", "tpu") == "cpu" and \
                not _host_convertible(node):
            # a host-native leaf/operator (HostScanExec, RangeExec, a
            # Cpu op already below a transition): placement keeps it
            out = (_INF,
                   sum(min(costs(c)[1], costs(c)[0] + down(c))
                       for c in node.children) if node.children else 0.0)
        else:
            kid_dev = kid_host = 0.0
            for c in node.children:
                cd, ch = costs(c)
                kid_dev += min(cd, ch + up(c))
                kid_host += min(ch, cd + down(c))
            if _host_convertible(node):
                host_self = coster.host_op(node)
            else:
                host_self = _INF
            if isinstance(node, TpuFileScanExec) and \
                    getattr(node, "_encoded_plan_cache", None) and \
                    not force_host:
                # encoded-dictionary claims describe DEVICE decode
                # output; auto mode never moves such a scan
                host_self = _INF
            dev = coster.dev_op(node) + kid_dev
            host = (host_self + kid_host) if host_self != _INF else _INF
            if force_host and host != _INF:
                dev = _INF
            out = (dev, host)
        memo[id(node)] = out
        return out

    root_dev, root_host = costs(plan)
    # the query's result is consumed on the host either way
    choose_host_root = root_host <= root_dev + down(plan) \
        if root_host != _INF else False
    if root_dev == _INF and root_host == _INF:
        report.reason = "no feasible placement (kept as planned)"
        return plan, report

    # -- realize the assignment ---------------------------------------------
    # `forced` marks a region inside a dissolved SPMD atom: the DP
    # priced that atom host WHOLESALE (its interior estimates describe
    # the single device program — dispatch counts of 0, free in-program
    # exchanges — and are meaningless for a device island), so every
    # node in the region goes host without per-node re-decision
    def realize(node, side: str, forced: bool = False):
        if isinstance(node, _TRANSITIONS):
            return realize(node.children[0], side, forced)
        if isinstance(node, _COALESCES):
            c = realize(node.children[0], side, forced)
            if c.placement == "tpu":
                return TpuCoalesceBatchesExec(node.goal, c)
            return CpuCoalesceBatchesExec(node.goal, c)
        if isinstance(node, XB.CoalescePartitionsExec):
            return XB.CoalescePartitionsExec(
                node.num_partitions,
                realize(node.children[0], side, forced))
        if _is_aqe_atom(node):
            return node
        cd, ch = costs(node)
        dec_side = side
        if side == "tpu" and cd == _INF:
            dec_side = "cpu"
        if side == "cpu" and ch == _INF and not forced:
            dec_side = "tpu"
        if isinstance(node, TpuSpmdStageExec):
            if dec_side == "cpu":
                return realize(node.children[0], "cpu", True)
            report.decisions.append(PlacementDecision(
                node.node_name(), "spmd-stage", cd, ch, "tpu",
                "spmd atom"))
            report.device_ops += 1
            return node
        if isinstance(node, TpuFusedStageExec):
            inp = node.input_node
            kid = realize(inp, "cpu" if forced or costs(inp)[1] <=
                          costs(inp)[0] + down(inp) else "tpu", forced)
            if dec_side == "cpu":
                twin = _host_equiv(node, (kid,))
                if twin is not None:
                    report.decisions.append(PlacementDecision(
                        node.node_name(), "fused-stage", cd, ch, "cpu",
                        "unfused"))
                    report.host_ops += len(node.members)
                    report.changed = True
                    return twin
            report.decisions.append(PlacementDecision(
                node.node_name(), "fused-stage", cd, ch, "tpu"))
            report.device_ops += 1
            if kid is not inp:
                # re-thread the member chain over the re-placed input,
                # then re-wrap: with_children would rebuild from the OLD
                # chain top and lose the new input
                cur = kid
                for m in reversed(node.members):
                    cur = m.with_children((cur,))
                return TpuFusedStageExec(node.stage_id, cur, node.n_ops)
            return node
        if getattr(node, "placement", "tpu") == "cpu" and \
                not _host_convertible(node):
            kids = tuple(
                realize(c, "cpu" if forced or costs(c)[1] <=
                        costs(c)[0] + down(c) else "tpu", forced)
                for c in node.children)
            if kids != node.children:
                return node.with_children(kids)
            return node
        if dec_side == "cpu":
            kids = tuple(
                realize(c, "cpu" if forced or costs(c)[1] <=
                        costs(c)[0] + down(c) else "tpu", forced)
                for c in node.children)
            twin = _host_equiv(node, kids)
            if twin is not None:
                if isinstance(twin, CpuShuffleExchangeExec):
                    twin = _coalesce_host_exchange(
                        twin, coster._rows(node))
                report.decisions.append(PlacementDecision(
                    node.node_name(), CAL.classify(node.node_name()),
                    cd, ch, "cpu"))
                report.host_ops += 1
                report.changed = True
                return twin
            # unreachable in practice (an inconvertible node prices
            # host=INF), but keep the device node rather than corrupt
        kids = tuple(
            realize(c, "tpu" if not forced and costs(c)[0] <=
                    costs(c)[1] + up(c) else "cpu", forced)
            for c in node.children)
        report.decisions.append(PlacementDecision(
            node.node_name(), CAL.classify(node.node_name()),
            cd, ch, "tpu"))
        report.device_ops += 1
        if kids != node.children:
            return node.with_children(kids)
        return node

    placed = realize(plan, "cpu" if choose_host_root else "tpu")
    report.predicted_ns = root_host if choose_host_root \
        else root_dev + down(plan)
    report.alt_device_ns = None if root_dev == _INF \
        else root_dev + down(plan)

    if not report.changed:
        report.reason = "all operators cheapest on device"
        return plan, report

    placed = _insert_transitions(placed, want_host_output=True)
    placed = _optimize_transitions(placed)
    report.boundaries = len(placed.collect_nodes(
        lambda n: isinstance(n, _TRANSITIONS)))
    return placed, report
