"""Window spec builder (the pyspark.sql.Window analog)."""

from __future__ import annotations

from typing import List, Optional, Union

from spark_rapids_tpu.ops.base import SortOrder
from spark_rapids_tpu.ops.window import (
    CURRENT_ROW,
    UNBOUNDED,
    WindowFrame,
    WindowSpec,
)
from spark_rapids_tpu.plan.column import Column, _to_expr

unboundedPreceding = UNBOUNDED
unboundedFollowing = UNBOUNDED
currentRow = CURRENT_ROW


class WindowBuilder:
    def __init__(self, partition_by=(), order_by=(), frame=None):
        self._partition_by = list(partition_by)
        self._order_by = list(order_by)
        self._frame = frame

    def partitionBy(self, *cols) -> "WindowBuilder":
        return WindowBuilder([_col(c) for c in cols], self._order_by,
                             self._frame)

    def orderBy(self, *cols) -> "WindowBuilder":
        orders = []
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(c)
            else:
                orders.append(SortOrder(_col(c), True))
        return WindowBuilder(self._partition_by, orders, self._frame)

    def rowsBetween(self, start, end) -> "WindowBuilder":
        lo = None if start is None else int(start)
        hi = None if end is None else int(end)
        return WindowBuilder(self._partition_by, self._order_by,
                             WindowFrame("rows", lo, hi))

    def rangeBetween(self, start, end) -> "WindowBuilder":
        """RANGE frame; bounds are ORDER-BY-value offsets (0 = CURRENT ROW,
        None = unbounded). Finite bounds need exactly one numeric ORDER BY
        column (reference: GpuWindowExpression.scala:457-683)."""
        lo = UNBOUNDED if start is None else int(start)
        hi = UNBOUNDED if end is None else int(end)
        return WindowBuilder(self._partition_by, self._order_by,
                             WindowFrame("range", lo, hi))

    def to_spec(self) -> WindowSpec:
        return WindowSpec(self._partition_by, self._order_by, self._frame)


def _col(c):
    if isinstance(c, str):
        from spark_rapids_tpu.plan.functions import col

        return col(c).expr
    if isinstance(c, Column):
        return c.expr
    return c


class _WindowModule:
    """`Window.partitionBy(...)` entry point."""

    unboundedPreceding = UNBOUNDED
    unboundedFollowing = UNBOUNDED
    currentRow = CURRENT_ROW

    @staticmethod
    def partitionBy(*cols) -> WindowBuilder:
        return WindowBuilder().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowBuilder:
        return WindowBuilder().orderBy(*cols)

    @staticmethod
    def rowsBetween(start, end) -> WindowBuilder:
        return WindowBuilder().rowsBetween(start, end)


Window = _WindowModule
