"""Post-pass transition insertion + optimization.

Reference parity: GpuTransitionOverrides.scala —
- insert host/device boundary nodes (:152-169) -> placement-boundary insertion
  of HostToDeviceExec / DeviceToHostExec.
- insert GpuCoalesceBatches per child CoalesceGoal (:64-147) ->
  coalesce-goal insertion.
- fuse adjacent transitions (:37-62) -> `_optimize_transitions`.
- `assertIsOnTheGpu` strict test mode with allow-list (:211-260) ->
  `assert_is_on_tpu`.
"""

from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.exec import basic as B
from spark_rapids_tpu.exec.base import CpuExec, PhysicalExec, TpuExec
from spark_rapids_tpu.exec.transitions import (
    CoalesceGoal,
    CpuCoalesceBatchesExec,
    DeviceToHostExec,
    HostToDeviceExec,
    TargetSize,
    TpuCoalesceBatchesExec,
)

# execs that pass batches through without touching placement
_TRANSPARENT = (B.CoalescePartitionsExec,)


def _effective_placement(node: PhysicalExec) -> str:
    if isinstance(node, _TRANSPARENT):
        return _effective_placement(node.children[0]) if node.children else "cpu"
    return node.placement


class TpuTransitionOverrides:
    """The post-transition columnar rule (reference: ColumnarOverrideRules
    postColumnarTransitions, Plugin.scala:41-43)."""

    @staticmethod
    def apply(plan: PhysicalExec, conf: C.TpuConf) -> PhysicalExec:
        plan = _insert_transitions(plan, want_host_output=True)
        plan = _insert_coalesce(plan, conf)
        plan = _optimize_transitions(plan)
        _pin_join_exchanges(plan)
        if conf.test_enabled:
            assert_is_on_tpu(plan, conf)
        return plan


def _pin_join_exchanges(node: PhysicalExec) -> None:
    """Disable adaptive partition coalescing on exchanges that feed a
    shuffled join: both join inputs must keep the SAME reduce grouping for
    pidx-by-pidx co-partitioning to hold (Spark AQE coordinates the two
    sides; here the exchanges simply stay at the planned partition count).
    Broadcast joins are unaffected — their build side is collected whole."""
    from spark_rapids_tpu.exec.join import (
        CpuShuffledHashJoinExec,
        TpuShuffledHashJoinExec,
    )
    from spark_rapids_tpu.shuffle.exchange import _ExchangeBase

    def pin_first_exchanges(n: PhysicalExec) -> None:
        if isinstance(n, _ExchangeBase):
            n.allow_adaptive = False
            return  # grouping below another exchange is independent
        for c in n.children:
            pin_first_exchanges(c)

    shuffled_join = (TpuShuffledHashJoinExec, CpuShuffledHashJoinExec)
    if isinstance(node, shuffled_join) and \
            not getattr(node, "broadcast", False):
        for c in node.children:
            pin_first_exchanges(c)
    for c in node.children:
        _pin_join_exchanges(c)


def _insert_transitions(node: PhysicalExec, want_host_output: bool) -> PhysicalExec:
    """Make batch placement consistent along every edge; the root must
    produce host batches when `want_host_output` (collect boundary,
    reference GpuBringBackToHost insertion)."""
    new_children = []
    for c in node.children:
        c2 = _insert_transitions(c, want_host_output=False)
        child_p = _effective_placement(c2)
        # transparent nodes adopt whatever the child produces
        my_p = _effective_placement(node) if isinstance(node, _TRANSPARENT) \
            else node.placement
        if my_p == "tpu" and child_p == "cpu":
            c2 = HostToDeviceExec(c2)
        elif my_p == "cpu" and child_p == "tpu" and \
                not isinstance(node, DeviceToHostExec):
            c2 = DeviceToHostExec(c2)
        new_children.append(c2)
    if new_children and any(
            a is not b for a, b in zip(new_children, node.children)):
        node = node.with_children(new_children)
    if want_host_output and _effective_placement(node) == "tpu":
        node = DeviceToHostExec(node)
    return node


def _has_input_file_expr(node: PhysicalExec) -> bool:
    def expr_has(e) -> bool:
        if getattr(e, "disable_coalesce_until_input", False):
            return True
        return any(expr_has(c) for c in e.children())

    return any(expr_has(e) for e in node.node_expressions())


def _is_new_input(node: PhysicalExec) -> bool:
    """Nodes that produce their own rows: coalescing above them can no
    longer mix rows from different files (reference: the disableUntilInput
    walk stops at exchanges/scans, GpuTransitionOverrides.scala:113-147)."""
    from spark_rapids_tpu.io.scan import _FileScanBase
    from spark_rapids_tpu.shuffle.exchange import _ExchangeBase

    return isinstance(node, (_ExchangeBase, _FileScanBase, B.HostScanExec,
                             B.RangeExec))


def _insert_coalesce(node: PhysicalExec, conf: C.TpuConf,
                     poisoned: bool = False) -> PhysicalExec:
    """Insert batch-coalescing per the child goals each operator declares
    (reference: GpuTransitionOverrides.insertCoalesce, :64-147). Edges
    BELOW a node evaluating an input-file expression (input_file_name()
    etc.), down to the producing input (scan/exchange), are POISONED: a
    coalesce there would merge batches across file boundaries before the
    expression reads which file each row came from (reference: :64-147
    input-file poisoning). Edges above the expression node are safe — the
    value is already materialized."""
    poisoned = poisoned or _has_input_file_expr(node)
    goals = node.children_coalesce_goal
    new_children = []
    for c, goal in zip(node.children, goals):
        # recursing INTO a new input clears the poison for ITS subtree;
        # the edge directly above the input is still poisoned
        c2 = _insert_coalesce(c, conf, poisoned and not _is_new_input(c))
        if goal is None and getattr(c2, "coalesce_after", False):
            goal = TargetSize(conf.batch_size_bytes)
        # poisoning drops only best-effort TargetSize coalesces; a
        # REQUIRED single-batch goal (sort/window/join-build correctness)
        # always wins over input-file file-attribution fidelity
        if goal is not None and not (poisoned and
                                     isinstance(goal, TargetSize)):
            if _effective_placement(c2) == "tpu":
                c2 = TpuCoalesceBatchesExec(goal, c2)
            else:
                c2 = CpuCoalesceBatchesExec(goal, c2)
        new_children.append(c2)
    if new_children and any(
            a is not b for a, b in zip(new_children, node.children)):
        node = node.with_children(new_children)
    return node


def insert_hash_optimize_sort(plan: PhysicalExec,
                              conf: C.TpuConf) -> PhysicalExec:
    """Optionally sort the output of hash-based operators feeding a file
    write, clustering equal keys so written files size/compress better
    (reference: GpuTransitionOverrides.insertHashOptimizeSorts, :171-204).
    Called by the write path on the write's input plan."""
    if not conf.get(C.HASH_OPTIMIZE_SORT):
        return plan
    from spark_rapids_tpu.exec.aggregate import _HashAggregateBase
    from spark_rapids_tpu.exec.join import (
        TpuShuffledHashJoinExec,
    )
    from spark_rapids_tpu.exec.sort import TpuSortExec
    from spark_rapids_tpu.exec.transitions import DeviceToHostExec as D2H
    from spark_rapids_tpu.ops.base import AttributeReference, SortOrder

    def sort_keys(n: PhysicalExec):
        from spark_rapids_tpu.plan.spmd import TpuSpmdStageExec

        if isinstance(n, TpuSpmdStageExec):
            # the stage program ends in the final hash aggregate (or an
            # absorbed sort, which already clusters): sort its output by
            # the grouping keys it actually emits
            info = n.info
            if info.sort is not None or not info.final.grouping:
                return None
            out_ids = {a.expr_id for a in n.output}
            return [a for a in info.final.grouping
                    if isinstance(a, AttributeReference)
                    and a.expr_id in out_ids]
        if isinstance(n, _HashAggregateBase) and n.grouping:
            return [a for a in n.grouping
                    if isinstance(a, AttributeReference)]
        if isinstance(n, TpuShuffledHashJoinExec):
            return [a for a in getattr(n, "left_keys", [])
                    if isinstance(a, AttributeReference)]
        return None

    def rewrite(n: PhysicalExec) -> PhysicalExec:
        # walk through the transitions/coalesces directly under the write
        if isinstance(n, (D2H, TpuCoalesceBatchesExec,
                          CpuCoalesceBatchesExec)):
            child = rewrite(n.children[0])
            if child is not n.children[0]:
                return n.with_children([child])
            return n
        keys = sort_keys(n)
        if keys and _effective_placement(n) == "tpu":
            from spark_rapids_tpu.exec.transitions import RequireSingleBatch

            orders = [SortOrder(k, True) for k in keys]
            # this pass runs after coalesce insertion, so the sort's
            # single-batch requirement must be materialized here — a
            # per-batch sort would not cluster keys across batches
            return TpuSortExec(
                orders, TpuCoalesceBatchesExec(RequireSingleBatch(), n))
        return n

    return rewrite(plan)


def _optimize_transitions(node: PhysicalExec) -> PhysicalExec:
    """Drop adjacent DeviceToHost(HostToDevice(x)) / HostToDevice(DeviceToHost(x))
    pairs (reference: optimizeGpuPlanTransitions, :37-44)."""

    def fuse(n: PhysicalExec) -> PhysicalExec:
        if isinstance(n, DeviceToHostExec) and \
                isinstance(n.children[0], HostToDeviceExec):
            return n.children[0].children[0]
        if isinstance(n, HostToDeviceExec) and \
                isinstance(n.children[0], DeviceToHostExec):
            return n.children[0].children[0]
        # merge nested same-placement coalesces, keep the stronger goal
        if isinstance(n, TpuCoalesceBatchesExec) and \
                isinstance(n.children[0], TpuCoalesceBatchesExec):
            inner = n.children[0]
            return TpuCoalesceBatchesExec(n.goal.max_combine(inner.goal),
                                          inner.children[0])
        return n

    return node.transform_up(fuse)


class NotOnTpuError(AssertionError):
    pass


def assert_is_on_tpu(plan: PhysicalExec, conf: C.TpuConf) -> None:
    """Strict test mode: every operator must be a TPU exec unless allowed
    (reference: GpuTransitionOverrides.assertIsOnTheGpu, :211-260)."""
    allowed = set(conf.allowed_non_tpu)
    always_ok = {
        "HostScanExec", "RangeExec", "DeviceToHostExec", "HostToDeviceExec",
        "CoalescePartitionsExec", "CpuCoalesceBatchesExec",
    }

    def check(n: PhysicalExec) -> None:
        name = type(n).__name__
        if isinstance(n, CpuExec) and name not in always_ok and \
                name not in allowed:
            raise NotOnTpuError(
                f"{name} did not run on the TPU; plan:\n{plan.tree_string()}")

    plan.foreach(check)
