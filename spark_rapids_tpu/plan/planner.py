"""Logical -> CPU physical planning.

The reference relies on Spark Catalyst to produce the CPU physical plan and
only *rewrites* it (GpuOverrides); standalone, we need the (simple) physical
planner itself. The CPU plan produced here is the oracle engine; the
TpuOverrides pass (plan/overrides.py) then replaces supported nodes with TPU
execs, exactly like the reference replaces Spark execs with Gpu execs.

Distribution planning mirrors Spark:
- Aggregate -> partial agg + hash exchange on keys + final agg
  (reference call stack section 3.5).
- Global sort -> range exchange + per-partition sort (GpuSortExec.scala:50-98).
- Equi-join -> broadcast hash join when one side fits under the threshold,
  else hash exchange both sides + shuffled hash join
  (GpuShuffledHashJoinExec / GpuBroadcastHashJoinExec).
- Global limit -> local limit + single-partition exchange + global limit
  (GpuCollectLimitMeta, limit.scala:124).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Type

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.exec import basic as B
from spark_rapids_tpu.exec.base import PhysicalExec
from spark_rapids_tpu.plan import logical as L

# dispatch table, extended by feature modules (aggregate/sort/join/io/...)
_PLANNERS: Dict[Type[L.LogicalPlan], Callable] = {}


def register_planner(logical_cls: Type[L.LogicalPlan]):
    def deco(fn):
        _PLANNERS[logical_cls] = fn
        return fn
    return deco


def plan_physical(plan: L.LogicalPlan, conf: C.TpuConf) -> PhysicalExec:
    fn = _PLANNERS.get(type(plan))
    if fn is None:
        raise NotImplementedError(
            f"no physical planning for {type(plan).__name__}")
    return fn(plan, conf)


def _plan_children(plan: L.LogicalPlan, conf: C.TpuConf) -> List[PhysicalExec]:
    return [plan_physical(c, conf) for c in plan.children]


@register_planner(L.LocalRelation)
def _plan_local(plan: L.LocalRelation, conf: C.TpuConf) -> PhysicalExec:
    return B.HostScanExec(plan.schema, plan.partitions)


@register_planner(L.RangeRelation)
def _plan_range(plan: L.RangeRelation, conf: C.TpuConf) -> PhysicalExec:
    return B.RangeExec(plan.start, plan.end, plan.step, plan.num_partitions,
                       plan.output[0])


@register_planner(L.Project)
def _plan_project(plan: L.Project, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    return _project_with_windows(plan.project_list, child, conf)


def _project_with_windows(project_list, child: PhysicalExec,
                          conf: C.TpuConf) -> PhysicalExec:
    """Extract window expressions into window exec nodes below the project
    (reference: GpuWindowExec meta extracting window exprs from nested
    projects, GpuWindowExec.scala:33-91). One window exec per distinct
    (partition_by, order_by) spec."""
    from spark_rapids_tpu.exec.window import CpuWindowExec
    from spark_rapids_tpu.ops.base import Alias
    from spark_rapids_tpu.ops.window import WindowExpression

    wnodes = []
    for e in project_list:
        wnodes.extend(e.collect(lambda n: isinstance(n, WindowExpression)))
    if not wnodes:
        return B.CpuProjectExec(project_list, child)
    by_fp = {}
    attr_of = {}
    for w in wnodes:
        fp = w.fingerprint()
        if fp in by_fp:
            continue
        alias = Alias(w, f"_w{len(by_fp)}")
        by_fp[fp] = alias
        from spark_rapids_tpu.ops.base import to_attribute

        attr_of[fp] = to_attribute(alias)
    # group by sort identity (partition+order)
    groups = {}
    for fp, alias in by_fp.items():
        w = alias.child
        skey = (tuple(e.fingerprint() for e in w.spec.partition_by),
                tuple(o.fingerprint() for o in w.spec.order_by))
        groups.setdefault(skey, []).append(alias)
    node = child
    for aliases in groups.values():
        node = CpuWindowExec(
            aliases, _window_distribution(aliases[0].child.spec, node, conf))

    def rewrite(e):
        if isinstance(e, WindowExpression):
            return attr_of[e.fingerprint()]
        return e

    rewritten = [e.transform_up(rewrite) for e in project_list]
    return B.CpuProjectExec(rewritten, node)


def _window_distribution(spec, child: PhysicalExec,
                         conf: C.TpuConf) -> PhysicalExec:
    """Window requires all rows of a partition key in one task partition
    (reference: GpuWindowExec requiredChildDistribution = ClusteredDistribution
    on partitionSpec): hash-exchange on partition_by, or collapse to a single
    partition when partition_by is empty."""
    from spark_rapids_tpu.shuffle.exchange import (
        CpuShuffleExchangeExec,
        HashPartitioning,
        SinglePartitioning,
    )

    if spec.partition_by:
        part = HashPartitioning(list(spec.partition_by),
                                conf.shuffle_partitions)
    else:
        part = SinglePartitioning()
    return CpuShuffleExchangeExec(part, child)


@register_planner(L.WindowOp)
def _plan_window(plan: L.WindowOp, conf: C.TpuConf) -> PhysicalExec:
    from spark_rapids_tpu.exec.window import CpuWindowExec, _unwrap

    (child,) = _plan_children(plan, conf)
    spec = _unwrap(plan.window_exprs[0]).spec
    return CpuWindowExec(plan.window_exprs,
                         _window_distribution(spec, child, conf))


@register_planner(L.Filter)
def _plan_filter(plan: L.Filter, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    return B.CpuFilterExec(plan.condition, child)


@register_planner(L.Union)
def _plan_union(plan: L.Union, conf: C.TpuConf) -> PhysicalExec:
    return B.CpuUnionExec(*_plan_children(plan, conf))


@register_planner(L.Limit)
def _plan_limit(plan: L.Limit, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    local = B.CpuLocalLimitExec(plan.n, child)
    merged = B.CoalescePartitionsExec(1, local)
    return B.CpuGlobalLimitExec(plan.n, merged)


@register_planner(L.Repartition)
def _plan_repartition(plan: L.Repartition, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    if plan.coalesce_only:
        return B.CoalescePartitionsExec(plan.num_partitions or 1, child)
    from spark_rapids_tpu.shuffle.exchange import plan_repartition_exchange

    return plan_repartition_exchange(plan, child, conf)


@register_planner(L.FileScan)
def _plan_file_scan(plan: L.FileScan, conf: C.TpuConf) -> PhysicalExec:
    from spark_rapids_tpu.io.scan import CpuFileScanExec, plan_splits

    splits = plan_splits(plan.fmt, plan.paths, plan.options, conf,
                         files=plan.files)
    return CpuFileScanExec(plan.output, splits, plan.fmt)


@register_planner(L.CacheRelation)
def _plan_cache(plan: L.CacheRelation, conf: C.TpuConf) -> PhysicalExec:
    from spark_rapids_tpu.exec.cache import CpuCachedScanExec

    (child,) = _plan_children(plan, conf)
    return CpuCachedScanExec(plan, child)


@register_planner(L.Aggregate)
def _plan_aggregate(plan: L.Aggregate, conf: C.TpuConf) -> PhysicalExec:
    """partial agg -> hash exchange on keys -> final agg (reference call
    stack SURVEY.md section 3.5; ungrouped reductions exchange to one
    partition)."""
    from spark_rapids_tpu.exec.aggregate import (
        FINAL,
        PARTIAL,
        CpuHashAggregateExec,
        build_agg_specs,
    )
    from spark_rapids_tpu.shuffle.exchange import (
        CpuShuffleExchangeExec,
        HashPartitioning,
        SinglePartitioning,
    )

    (child,) = _plan_children(plan, conf)
    specs = build_agg_specs(plan.agg_exprs)
    if any(getattr(s.func, "holistic", False) for s in specs):
        # holistic aggregates (percentile) are not update/merge
        # decomposable: exchange RAW rows on the grouping keys and run ONE
        # complete-mode aggregation (Spark's ObjectHashAggregate shape; the
        # exec declares RequireSingleBatch so each partition aggregates
        # exactly once)
        from spark_rapids_tpu.exec.aggregate import (
            COMPLETE,
            _key_exprs_for,
        )

        if plan.grouping:
            part = HashPartitioning(
                _key_exprs_for(plan.grouping, plan.agg_exprs),
                conf.shuffle_partitions)
        else:
            # KNOWN SCALE LIMIT: a global (ungrouped) holistic percentile
            # routes the ENTIRE input through one partition and one device
            # batch (SinglePartitioning + RequireSingleBatch). Correct —
            # the unmergeable op fails loudly if violated — but a cliff at
            # large SF; grouped percentiles scale normally. A two-level
            # scheme (per-partition sorted runs merged on the driver)
            # is the upgrade path if a workload needs a global percentile
            # over more rows than one batch holds.
            part = SinglePartitioning()
        exchange = CpuShuffleExchangeExec(part, child)
        return CpuHashAggregateExec(plan.grouping, plan.agg_exprs, COMPLETE,
                                    exchange, specs)
    partial = CpuHashAggregateExec(plan.grouping, plan.agg_exprs, PARTIAL,
                                   child, specs)
    if plan.grouping:
        part = HashPartitioning(list(plan.grouping), conf.shuffle_partitions)
    else:
        part = SinglePartitioning()
    exchange = CpuShuffleExchangeExec(part, partial)
    return CpuHashAggregateExec(plan.grouping, plan.agg_exprs, FINAL,
                                exchange, specs)


@register_planner(L.Expand)
def _plan_expand(plan: L.Expand, conf: C.TpuConf) -> PhysicalExec:
    """Grouping sets: one projection list per set (reference:
    GpuExpandExec.scala:66-102)."""
    from spark_rapids_tpu.exec.expand import CpuExpandExec

    (child,) = _plan_children(plan, conf)
    return CpuExpandExec(plan.projections, plan.output_attrs, child)


@register_planner(L.Generate)
def _plan_generate(plan: L.Generate, conf: C.TpuConf) -> PhysicalExec:
    """explode/posexplode of a created array (reference:
    GpuGenerateExec.scala:101)."""
    from spark_rapids_tpu.exec.expand import CpuGenerateExec

    (child,) = _plan_children(plan, conf)
    gen = plan.generator
    return CpuGenerateExec(gen.include_pos, list(gen.array.elems),
                           plan.generator_output, child)


@register_planner(L.Sort)
def _plan_sort(plan: L.Sort, conf: C.TpuConf) -> PhysicalExec:
    """Global sort = range exchange + per-partition sort
    (reference: GpuSortExec.scala:50-98)."""
    from spark_rapids_tpu.exec.sort import CpuSortExec
    from spark_rapids_tpu.shuffle.exchange import (
        CpuShuffleExchangeExec,
        RangePartitioning,
    )

    (child,) = _plan_children(plan, conf)
    if plan.is_global:
        child = CpuShuffleExchangeExec(
            RangePartitioning(plan.orders, conf.shuffle_partitions), child)
    return CpuSortExec(plan.orders, child)


def _estimate_rows(plan: L.LogicalPlan):
    """Best-effort UPPER-BOUND row estimate for the broadcast-join decision
    (the reference rides Spark's statistics; this is the standalone
    stand-in). Descends through joins (equi inner/outer output is bounded
    by the larger side times matches — approximated by max, the FK-join
    case), aggregates (grouped output <= input), and cached relations
    (exact counts once materialized), so multi-join plans like TPC-H q7
    can statically broadcast their small intermediate sides instead of
    re-exchanging the fact stream at every level."""
    if isinstance(plan, L.LocalRelation):
        return sum(b.num_rows for part in plan.partitions for b in part)
    if isinstance(plan, L.RangeRelation):
        step = plan.step or 1
        return max(0, (plan.end - plan.start + step - 1) // step)
    if isinstance(plan, L.Limit):
        child = _estimate_rows(plan.children[0])
        return plan.n if child is None else min(plan.n, child)
    if isinstance(plan, (L.Project, L.Filter, L.Sort, L.Repartition,
                         L.WindowOp, L.Aggregate)):
        return _estimate_rows(plan.children[0])
    if isinstance(plan, L.CacheRelation):
        from spark_rapids_tpu.exec.cache import cached_row_count

        n = cached_row_count(plan)
        return n if n is not None else _estimate_rows(plan.children[0])
    if isinstance(plan, L.Union):
        parts = [_estimate_rows(c) for c in plan.children]
        return None if any(p is None for p in parts) else sum(parts)
    if isinstance(plan, L.Expand):
        child = _estimate_rows(plan.children[0])
        return None if child is None else child * max(
            len(plan.projections), 1)
    if isinstance(plan, L.Join):
        if plan.join_type is L.JoinType.CROSS:
            l, r = (_estimate_rows(c) for c in plan.children)
            return None if l is None or r is None else l * r
        if plan.join_type in (L.JoinType.LEFT_SEMI, L.JoinType.LEFT_ANTI):
            # filtering joins never emit more than their left input
            return _estimate_rows(plan.children[0])
        # Equi-join output is NOT boundable from input sizes (an m:n key
        # reaches l*r); a statically-planned broadcast has no runtime
        # size guard, so joins deliberately estimate unknown here. A
        # small JOINED build side still broadcasts at runtime: the
        # shuffled plan's runtime_broadcast_probe (exec/join.py) decides
        # on the build's ACTUAL materialized bytes.
        return None
    return None


@register_planner(L.Join)
def _plan_join(plan: L.Join, conf: C.TpuConf) -> PhysicalExec:
    from spark_rapids_tpu.exec.join import (
        CpuBroadcastHashJoinExec,
        CpuNestedLoopJoinExec,
        CpuShuffledHashJoinExec,
    )
    from spark_rapids_tpu.shuffle.exchange import (
        CpuShuffleExchangeExec,
        HashPartitioning,
    )

    left, right = _plan_children(plan, conf)
    jt = plan.join_type
    if jt is L.JoinType.CROSS or not plan.left_keys:
        if jt not in (L.JoinType.CROSS, L.JoinType.INNER):
            raise NotImplementedError(
                f"non-equi {jt.value} join is not supported")
        return CpuNestedLoopJoinExec([], [], L.JoinType.CROSS,
                                     plan.condition, left, right)
    if plan.condition is not None and jt is not L.JoinType.INNER:
        raise NotImplementedError(
            f"{jt.value} join with a non-equi residual condition")

    # co-partitioning + key equality require both key lists to share a type
    from spark_rapids_tpu.columnar.dtypes import common_type
    from spark_rapids_tpu.ops.cast import Cast

    left_keys, right_keys = [], []
    for lk, rk in zip(plan.left_keys, plan.right_keys):
        if lk.data_type != rk.data_type:
            ct = common_type(lk.data_type, rk.data_type)
            if ct is None:
                raise NotImplementedError(
                    f"join keys of types {lk.data_type}/{rk.data_type}")
            lk = lk if lk.data_type == ct else Cast(lk, ct)
            rk = rk if rk.data_type == ct else Cast(rk, ct)
        left_keys.append(lk)
        right_keys.append(rk)

    # broadcast decision on the build side (right, or left for right-outer);
    # full outer cannot broadcast (unmatched-build tail would duplicate)
    def est_bytes_of(side_logical):
        est = _estimate_rows(side_logical)
        if est is None:
            return None
        return est * max(1, sum(a.data_type.itemsize
                                for a in side_logical.output))

    build_is_left = jt is L.JoinType.RIGHT_OUTER
    build_logical = plan.children[0] if build_is_left else plan.children[1]
    est_bytes = est_bytes_of(build_logical)
    threshold = conf.get(C.BROADCAST_THRESHOLD)
    if jt is not L.JoinType.FULL_OUTER and est_bytes is not None and \
            est_bytes <= threshold:
        return CpuBroadcastHashJoinExec(left_keys, right_keys, jt,
                                        plan.condition, left, right)
    if jt is L.JoinType.INNER and not build_is_left:
        # an INNER join can build on either side: when the right side is
        # too big (or unbounded) but the LEFT estimates under the
        # threshold, swap the children and broadcast — then restore the
        # original column order with a projection. This is the static
        # form of the runtime probe's build-side swap (exec/join.py
        # runtime_broadcast_probe), reached without materializing the big
        # side first; reference analog: Spark planning BroadcastHashJoin
        # with BuildLeft from statistics.
        from spark_rapids_tpu.exec.basic import CpuProjectExec

        left_bytes = est_bytes_of(plan.children[0])
        if left_bytes is not None and left_bytes <= threshold:
            swapped = CpuBroadcastHashJoinExec(
                right_keys, left_keys, jt, plan.condition, right, left)
            out = list(left.output) + list(right.output)
            return CpuProjectExec(out, swapped)
    n = conf.shuffle_partitions
    left_ex = CpuShuffleExchangeExec(HashPartitioning(left_keys, n), left)
    right_ex = CpuShuffleExchangeExec(HashPartitioning(right_keys, n), right)
    return CpuShuffledHashJoinExec(left_keys, right_keys, jt,
                                   plan.condition, left_ex, right_ex)
