"""Logical -> CPU physical planning.

The reference relies on Spark Catalyst to produce the CPU physical plan and
only *rewrites* it (GpuOverrides); standalone, we need the (simple) physical
planner itself. The CPU plan produced here is the oracle engine; the
TpuOverrides pass (plan/overrides.py) then replaces supported nodes with TPU
execs, exactly like the reference replaces Spark execs with Gpu execs.

Distribution planning mirrors Spark:
- Aggregate -> partial agg + hash exchange on keys + final agg
  (reference call stack section 3.5).
- Global sort -> range exchange + per-partition sort (GpuSortExec.scala:50-98).
- Equi-join -> broadcast hash join when one side fits under the threshold,
  else hash exchange both sides + shuffled hash join
  (GpuShuffledHashJoinExec / GpuBroadcastHashJoinExec).
- Global limit -> local limit + single-partition exchange + global limit
  (GpuCollectLimitMeta, limit.scala:124).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Type

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.exec import basic as B
from spark_rapids_tpu.exec.base import PhysicalExec
from spark_rapids_tpu.plan import logical as L

# dispatch table, extended by feature modules (aggregate/sort/join/io/...)
_PLANNERS: Dict[Type[L.LogicalPlan], Callable] = {}


def register_planner(logical_cls: Type[L.LogicalPlan]):
    def deco(fn):
        _PLANNERS[logical_cls] = fn
        return fn
    return deco


def plan_physical(plan: L.LogicalPlan, conf: C.TpuConf) -> PhysicalExec:
    fn = _PLANNERS.get(type(plan))
    if fn is None:
        raise NotImplementedError(
            f"no physical planning for {type(plan).__name__}")
    return fn(plan, conf)


def _plan_children(plan: L.LogicalPlan, conf: C.TpuConf) -> List[PhysicalExec]:
    return [plan_physical(c, conf) for c in plan.children]


@register_planner(L.LocalRelation)
def _plan_local(plan: L.LocalRelation, conf: C.TpuConf) -> PhysicalExec:
    return B.HostScanExec(plan.schema, plan.partitions)


@register_planner(L.RangeRelation)
def _plan_range(plan: L.RangeRelation, conf: C.TpuConf) -> PhysicalExec:
    return B.RangeExec(plan.start, plan.end, plan.step, plan.num_partitions,
                       plan.output[0])


@register_planner(L.Project)
def _plan_project(plan: L.Project, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    return B.CpuProjectExec(plan.project_list, child)


@register_planner(L.Filter)
def _plan_filter(plan: L.Filter, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    return B.CpuFilterExec(plan.condition, child)


@register_planner(L.Union)
def _plan_union(plan: L.Union, conf: C.TpuConf) -> PhysicalExec:
    return B.CpuUnionExec(*_plan_children(plan, conf))


@register_planner(L.Limit)
def _plan_limit(plan: L.Limit, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    local = B.CpuLocalLimitExec(plan.n, child)
    merged = B.CoalescePartitionsExec(1, local)
    return B.CpuGlobalLimitExec(plan.n, merged)


@register_planner(L.Repartition)
def _plan_repartition(plan: L.Repartition, conf: C.TpuConf) -> PhysicalExec:
    (child,) = _plan_children(plan, conf)
    if plan.coalesce_only:
        return B.CoalescePartitionsExec(plan.num_partitions or 1, child)
    from spark_rapids_tpu.shuffle.exchange import plan_repartition_exchange

    return plan_repartition_exchange(plan, child, conf)
