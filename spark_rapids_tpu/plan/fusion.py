"""Whole-stage fusion pass (the WholeStageCodegen planning analog).

Runs on the FINAL physical plan (after TpuOverrides conversion and
transition/coalesce insertion): greedily groups maximal chains of fusable,
pipelined TPU operators into `TpuFusedStageExec` nodes (exec/fused.py), so
each stage executes as ONE composed XLA program instead of one program (plus
intermediate batch) per operator.

Stage membership:
- scan form: TpuFilter / TpuProject / TpuExpand / TpuLocalLimit chains with
  deterministic, non-ANSI, non-input-file expressions; at most one Expand
  and one LocalLimit per stage (an Expand multiplies the program into one
  static variant per projection list; a second limit would need a second
  cross-batch budget operand).
- aggregate form: a partial/complete TpuHashAggregate tops the stage; its
  update kernel already folds the Project/Filter chain below it into one
  trace (exec/aggregate._collapse_scan_chain, gated on the same conf), so
  the pass wraps aggregate + chain for stage accounting.

Fusion barriers — anything else terminates a stage, mirroring the
reference's coalesce-goal boundaries: shuffle exchanges, joins, sorts,
windows, host<->device transitions, batch coalesces, scans, caches, and the
merge/final side of aggregates (blocking, not pipelined).

Conf: rapids.tpu.sql.fusion.enabled (default on),
rapids.tpu.sql.fusion.maxOps (stage size guard).
"""

from __future__ import annotations

import itertools

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.exec import basic as B
from spark_rapids_tpu.exec.base import PhysicalExec
from spark_rapids_tpu.exec.fused import (
    TpuFusedStageExec,
    exprs_fusable,
    is_fusable_scan_node,
)


def _scan_member(node: PhysicalExec) -> bool:
    return is_fusable_scan_node(node) and \
        exprs_fusable(node.node_expressions())


def _agg_chain_member(node: PhysicalExec) -> bool:
    """What the aggregate's update-kernel collapse walks through: projects,
    filters, and best-effort TargetSize coalesces (a RequireSingleBatch
    coalesce is semantic — holistic aggregates — and blocks the stage)."""
    from spark_rapids_tpu.exec.transitions import TpuCoalesceBatchesExec

    if isinstance(node, TpuCoalesceBatchesExec):
        return node.goal.target_bytes() is not None
    return isinstance(node, (B.TpuFilterExec, B.TpuProjectExec)) and \
        exprs_fusable(node.node_expressions())


def agg_stage_len(node: PhysicalExec, max_ops: int) -> int:
    """Chain length (agg included) of an aggregate-form stage rooted at
    `node`, or 0 when the node does not head a fusable aggregate stage."""
    from spark_rapids_tpu.columnar.dtypes import DataType
    from spark_rapids_tpu.exec.aggregate import (
        COMPLETE,
        PARTIAL,
        TpuHashAggregateExec,
    )

    if not isinstance(node, TpuHashAggregateExec) or \
            node.mode not in (PARTIAL, COMPLETE):
        return 0
    exprs = list(node.key_exprs) + [e for _, e, _ in node._update_ops()]
    if not exprs_fusable(exprs):
        return 0
    n_ops = 1
    real_members = 0
    has_project = False
    cur = node.children[0]
    while n_ops < max_ops and _agg_chain_member(cur):
        if isinstance(cur, (B.TpuFilterExec, B.TpuProjectExec)):
            real_members += 1
            has_project = has_project or isinstance(cur, B.TpuProjectExec)
        n_ops += 1
        cur = cur.children[0]
    if real_members == 0:
        return 0
    if has_project and any(
            op in ("min", "max") and e.data_type is DataType.STRING
            for op, e, _ in node._update_ops()):
        # the update kernel's string min/max needs plain-column inputs for
        # its static length bound; a project in the chain may substitute a
        # computed expression there and the runtime collapse would bail —
        # don't claim a stage the kernel may not fuse
        return 0
    return n_ops


def _scan_stage_len(node: PhysicalExec, max_ops: int) -> int:
    """Chain length of a scan-form stage rooted at `node` (0 = no stage)."""
    from spark_rapids_tpu.exec.expand import TpuExpandExec

    if not _scan_member(node):
        return 0
    n_ops = 0
    n_expand = n_limit = 0
    cur = node
    while n_ops < max_ops and _scan_member(cur):
        if isinstance(cur, TpuExpandExec):
            if n_expand:
                break
            n_expand += 1
        if isinstance(cur, B.TpuLocalLimitExec):
            if n_limit:
                break
            n_limit += 1
        n_ops += 1
        cur = cur.children[0]
    return n_ops if n_ops >= 2 else 0


def _rebuild_chain(top: PhysicalExec, n_ops: int,
                   new_input: PhysicalExec) -> PhysicalExec:
    if n_ops == 0:
        return new_input
    child = _rebuild_chain(top.children[0], n_ops - 1, new_input)
    if child is top.children[0]:
        return top
    return top.with_children([child])


def _chain_input(top: PhysicalExec, n_ops: int) -> PhysicalExec:
    node = top
    for _ in range(n_ops):
        node = node.children[0]
    return node


def fuse_stages(plan: PhysicalExec, conf: C.TpuConf) -> PhysicalExec:
    if not conf.get(C.FUSION_ENABLED):
        return plan
    max_ops = conf.get(C.FUSION_MAX_OPS)
    counter = itertools.count(1)

    def walk(node: PhysicalExec) -> PhysicalExec:
        n_ops = agg_stage_len(node, max_ops) or \
            _scan_stage_len(node, max_ops)
        if n_ops:
            below = _chain_input(node, n_ops)
            new_top = _rebuild_chain(node, n_ops, walk(below))
            return TpuFusedStageExec(next(counter), new_top, n_ops)
        new_children = [walk(c) for c in node.children]
        if new_children and any(
                a is not b for a, b in zip(new_children, node.children)):
            node = node.with_children(new_children)
        return node

    return walk(plan)


def count_fused_stages(plan: PhysicalExec) -> int:
    return len(plan.collect_nodes(
        lambda n: isinstance(n, TpuFusedStageExec)))
