"""Logical optimizer: column pruning.

The reference rides Spark Catalyst, whose ColumnPruning rule narrows every
operator to the attributes its ancestors actually consume before the plugin
ever sees the plan (the GpuOverrides rewrite runs on an already-pruned
physical plan). Standalone, this pass plays that role: without it every
join/exchange/aggregate drags the full scan schema — at TPC-H SF1 that is
all 16 lineitem columns (3 of them strings) flowing through 4 exchanges in
q7 when the query needs 5 numeric ones.

Design: one top-down walk carrying the set of attribute expr_ids the parent
may reference (`None` = everything). Each node keeps `output ∩ required`
plus whatever its own expressions reference, and rebuilds itself over pruned
children. Leaves narrow in place (FileScan schema feeds the readers'
column selection; LocalRelation drops host column buffers zero-copy);
CacheRelation is a shared materialization boundary, so pruning never pushes
below it — a Project lands ABOVE the cache instead.

Cardinality safety: pruning never drops a node that changes row counts
(Filter/Join/Aggregate/Generate/Expand/Limit stay put); a WindowOp whose
window columns are all unused IS dropped (windows are row-preserving).
A node pruned to zero columns keeps its narrowest attribute so batches
retain a row count carrier.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set

from spark_rapids_tpu import conf as C
from spark_rapids_tpu.ops.base import (
    Alias,
    AttributeReference,
    Expression,
    to_attribute,
)
from spark_rapids_tpu.plan import logical as L


def optimize(plan: L.LogicalPlan, conf: C.TpuConf) -> L.LogicalPlan:
    if conf.get(C.COLUMN_PRUNING):
        plan = _prune(plan, None)
    return plan


def _refs(exprs: Sequence[Expression]) -> Set[int]:
    out: Set[int] = set()
    for e in exprs:
        for a in e.collect(lambda n: isinstance(n, AttributeReference)):
            out.add(a.expr_id)
    return out


def _attr_cost(a: AttributeReference) -> int:
    dt = a.data_type
    return 64 if dt.is_string else dt.itemsize


def _narrowest(attrs: List[AttributeReference]) -> AttributeReference:
    """Row-count carrier when nothing is referenced: cheapest column wins
    (strings cost offsets + bytes, so any fixed-width beats them)."""
    return min(attrs, key=_attr_cost)


def _keep(attrs: List[AttributeReference],
          req: Optional[Set[int]]) -> List[AttributeReference]:
    if req is None:
        return list(attrs)
    kept = [a for a in attrs if a.expr_id in req]
    if not kept and attrs:
        kept = [_narrowest(attrs)]
    return kept


def _wrap_project(node: L.LogicalPlan,
                  req: Optional[Set[int]]) -> L.LogicalPlan:
    """Project `node` down to req (used above pruning barriers: cache)."""
    kept = _keep(node.output, req)
    if len(kept) == len(node.output):
        return node
    return L.Project(kept, node)


def _prune(plan: L.LogicalPlan,
           req: Optional[Set[int]]) -> L.LogicalPlan:
    t = type(plan)
    fn = _RULES.get(t)
    if fn is None:
        # unknown node: leave the whole subtree untouched (correct, unpruned)
        return plan
    return fn(plan, req)


_RULES = {}


def _rule(cls):
    def deco(fn):
        _RULES[cls] = fn
        return fn
    return deco


# --------------------------------------------------------------- leaves
@_rule(L.LocalRelation)
def _local(plan: L.LocalRelation, req):
    kept = _keep(plan.schema, req)
    if len(kept) == len(plan.schema):
        return plan
    idx = [i for i, a in enumerate(plan.schema)
           if a.expr_id in {k.expr_id for k in kept}]
    from spark_rapids_tpu.columnar.batch import HostColumnarBatch

    parts = [[HostColumnarBatch([b.columns[i] for i in idx], b.num_rows)
              for b in part] for part in plan.partitions]
    return L.LocalRelation(kept, parts)


@_rule(L.RangeRelation)
def _range(plan: L.RangeRelation, req):
    return plan


@_rule(L.FileScan)
def _file_scan(plan: L.FileScan, req):
    kept = _keep(plan.output, req)
    if len(kept) == len(plan.output):
        return plan
    if plan.fmt in ("parquet", "orc"):
        # columnar formats project by NAME: a narrowed schema means pruned
        # columns are never decoded (their chunks are skipped entirely)
        return L.FileScan(plan.fmt, plan.paths, kept, plan.options,
                          plan.files)
    # csv/json schemas are POSITIONAL (they define the file layout): the
    # scan must keep every field; prune right above it instead
    return _wrap_project(plan, req)


@_rule(L.CacheRelation)
def _cache(plan: L.CacheRelation, req):
    # the cached materialization is shared across queries; narrowing below
    # it would split the cache per consumer schema. Project above instead.
    return _wrap_project(plan, req)


# --------------------------------------------------------------- unary
@_rule(L.Project)
def _project(plan: L.Project, req):
    if req is None:
        kept = list(plan.project_list)
    else:
        kept = [e for e in plan.project_list
                if to_attribute(e).expr_id in req]
        if not kept:
            kept = [min(plan.project_list,
                        key=lambda e: 64 if e.data_type.is_string
                        else e.data_type.itemsize)]
    child = _prune(plan.children[0], _refs(kept))
    return L.Project(kept, child)


@_rule(L.Filter)
def _filter(plan: L.Filter, req):
    cond_refs = _refs([plan.condition])
    child_req = None if req is None else req | cond_refs
    pruned = L.Filter(plan.condition, _prune(plan.children[0], child_req))
    if req is not None and cond_refs - req:
        # condition-only columns the parent never asked for would otherwise
        # flow through every exchange/join between this Filter and the next
        # Project; Catalyst inserts the pruning Project in this position
        return _wrap_project(pruned, req)
    return pruned


@_rule(L.Limit)
def _limit(plan: L.Limit, req):
    return L.Limit(plan.n, _prune(plan.children[0], req))


@_rule(L.Repartition)
def _repartition(plan: L.Repartition, req):
    child_req = None if req is None else req | _refs(plan.partition_exprs)
    return L.Repartition(plan.num_partitions, plan.partition_exprs,
                         plan.coalesce_only,
                         _prune(plan.children[0], child_req))


@_rule(L.Sort)
def _sort(plan: L.Sort, req):
    child_req = None if req is None else \
        req | _refs([o.child for o in plan.orders])
    return L.Sort(plan.orders, plan.is_global,
                  _prune(plan.children[0], child_req))


@_rule(L.Aggregate)
def _aggregate(plan: L.Aggregate, req):
    grouping_ids = {to_attribute(g).expr_id for g in plan.grouping}
    if req is None:
        kept = list(plan.agg_exprs)
    else:
        # grouping-key computations must survive even when the key column
        # itself is unselected: grouping them determines output cardinality
        kept = [e for e in plan.agg_exprs
                if to_attribute(e).expr_id in req
                or to_attribute(e).expr_id in grouping_ids]
        if not kept:
            kept = list(plan.agg_exprs)
    child_req = _refs(kept) | _refs(plan.grouping)
    return L.Aggregate(plan.grouping, kept,
                       _prune(plan.children[0], child_req))


@_rule(L.WindowOp)
def _window(plan: L.WindowOp, req):
    if req is None:
        kept = list(plan.window_exprs)
    else:
        kept = [e for e in plan.window_exprs
                if to_attribute(e).expr_id in req]
    if not kept:
        # row-preserving node with no consumed outputs: drop it entirely
        return _prune(plan.children[0], req)
    child_req = None if req is None else req | _refs(kept)
    return L.WindowOp(kept, _prune(plan.children[0], child_req))


@_rule(L.Expand)
def _expand(plan: L.Expand, req):
    if req is None:
        keep_pos = list(range(len(plan.output_attrs)))
    else:
        keep_pos = [i for i, a in enumerate(plan.output_attrs)
                    if a.expr_id in req]
        if not keep_pos:
            # row-count carrier: same cost function as every other rule
            # (position 0 can be a string column — offsets + bytes through
            # every downstream exchange just to preserve cardinality)
            keep_pos = [min(range(len(plan.output_attrs)),
                            key=lambda i: _attr_cost(plan.output_attrs[i]))]
    projections = [[p[i] for i in keep_pos] for p in plan.projections]
    attrs = [plan.output_attrs[i] for i in keep_pos]
    child_req = _refs([e for p in projections for e in p])
    return L.Expand(projections, attrs, _prune(plan.children[0], child_req))


@_rule(L.Generate)
def _generate(plan: L.Generate, req):
    # the generator multiplies rows — the node always stays; only the
    # pass-through child columns narrow
    child_req = None if req is None else req | _refs([plan.generator])
    return L.Generate(plan.generator, plan.generator_output, plan.outer,
                      _prune(plan.children[0], child_req))


@_rule(L.WriteFile)
def _write(plan: L.WriteFile, req):
    # writers persist the child's full schema
    return L.WriteFile(plan.fmt, plan.path, plan.mode, plan.options,
                       plan.partition_by, _prune(plan.children[0], None))


# --------------------------------------------------------------- n-ary
@_rule(L.Union)
def _union(plan: L.Union, req):
    # positional alignment: prune the SAME positions in every child, then
    # pin each child's output order with an explicit Project
    first = plan.children[0].output
    if req is None:
        keep_pos = list(range(len(first)))
    else:
        keep_pos = [i for i, a in enumerate(first) if a.expr_id in req]
        if not keep_pos:
            keep_pos = [first.index(_narrowest(list(first)))]
    new_children = []
    for child in plan.children:
        attrs = [child.output[i] for i in keep_pos]
        pruned = _prune(child, {a.expr_id for a in attrs})
        if [a.expr_id for a in pruned.output] != \
                [a.expr_id for a in attrs]:
            pruned = L.Project(attrs, pruned)
        new_children.append(pruned)
    return L.Union(*new_children)


@_rule(L.Join)
def _join(plan: L.Join, req):
    needed = None
    if req is not None:
        needed = (req | _refs(plan.left_keys) | _refs(plan.right_keys)
                  | (_refs([plan.condition])
                     if plan.condition is not None else set()))
    return L.Join(_prune(plan.children[0], needed),
                  _prune(plan.children[1], needed),
                  plan.join_type, plan.left_keys, plan.right_keys,
                  plan.condition)
