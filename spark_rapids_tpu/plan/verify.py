"""Static physical-plan verifier: a type check over the FINAL plan.

The reference plugin's core safety net is static tagging — GpuOverrides
walks the plan and PROVES each operator can run before anything executes.
Whole-stage fusion (PR 1) raised the cost of the hazards tagging cannot
see: schema drift across fused stage boundaries, stale column references
after pruning, fused-stage accounting that disagrees with the member
chain, and host/device edges missing a transition node. This module is
the machine check for those: schema (name, dtype, nullability) propagates
bottom-up through the plan — INCLUDING the member chains inside
`TpuFusedStageExec` — and any plan whose declared outputs, references, or
stage accounting don't line up is rejected before a single kernel runs.

Wired into the rewrite path (session._physical_plan) behind
`rapids.tpu.sql.planVerify.enabled` and rendered by EXPLAIN
(`== Plan verification ==` section). `planVerify.failOnViolation=false`
switches to observe-only: violations surface in EXPLAIN instead of
raising (docs/static-analysis.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import PhysicalExec
from spark_rapids_tpu.ops.base import AttributeReference, Expression


class PlanViolation(str):
    """One static-analysis violation record. A plain `str` (every existing
    consumer formats/joins violations as strings) carrying a `kind` tag, so
    the plan verifier and the resource analyzer (plan/resources.py) share
    one record type and one reporting path (session.last_plan_violations)."""

    kind: str

    def __new__(cls, msg: str, kind: str = "PLAN_VERIFY") -> "PlanViolation":
        self = super().__new__(cls, msg)
        self.kind = kind
        return self


class PlanVerificationError(ValueError):
    """A physical plan failed static verification."""

    def __init__(self, violations: List[str]):
        self.violations = list(violations)
        super().__init__(
            "physical plan failed static verification:\n  - "
            + "\n  - ".join(self.violations))


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------
def _attr_map(attrs) -> Dict[int, AttributeReference]:
    return {a.expr_id: a for a in attrs}


def _refs(e: Expression) -> List[AttributeReference]:
    """AttributeReference leaves of an expression tree (the columns it
    consumes from its input)."""
    return e.collect(lambda x: isinstance(x, AttributeReference))


def _check_refs(node_name: str, exprs, available: Dict[int, AttributeReference],
                out: List[str], what: str = "expression") -> None:
    for e in exprs:
        for ref in _refs(e):
            have = available.get(ref.expr_id)
            if have is None:
                out.append(
                    f"{node_name}: {what} references column "
                    f"{ref.name}#{ref.expr_id} which no child produces "
                    "(column-pruning/rewrite drift)")
            elif have.data_type != ref.data_type:
                out.append(
                    f"{node_name}: {what} reads {ref.name}#{ref.expr_id} "
                    f"as {ref.data_type} but the child produces "
                    f"{have.data_type} (dtype drift)")
            elif not ref.nullable and have.nullable:
                out.append(
                    f"{node_name}: {what} assumes {ref.name}#{ref.expr_id}"
                    " is non-nullable but the child declares it nullable")


def _check_identity_schema(node: PhysicalExec, out: List[str]) -> None:
    child = node.children[0]
    mine, theirs = node.output, child.output
    if [a.expr_id for a in mine] != [a.expr_id for a in theirs] or \
            [a.data_type for a in mine] != [a.data_type for a in theirs]:
        out.append(
            f"{node.node_name()}: row-preserving operator declares an "
            f"output schema {_schema_str(mine)} different from its "
            f"child's {_schema_str(theirs)}")


def _schema_str(attrs) -> str:
    return "[" + ", ".join(f"{a.name}:{getattr(a.data_type, 'name', a.data_type)}"
                           for a in attrs) + "]"


def _expr_dtype(e: Expression):
    try:
        return e.data_type
    except Exception:  # noqa: BLE001 - a raising property IS the finding
        return None


# ---------------------------------------------------------------------------
# Per-node checks
# ---------------------------------------------------------------------------
def _check_node(node: PhysicalExec, out: List[str]) -> None:
    from spark_rapids_tpu.exec import basic as B
    from spark_rapids_tpu.exec.aggregate import _HashAggregateBase
    from spark_rapids_tpu.exec.expand import _ExpandBase
    from spark_rapids_tpu.exec.fused import TpuFusedStageExec
    from spark_rapids_tpu.exec.join import _JoinBase
    from spark_rapids_tpu.exec.sort import _SortBase
    from spark_rapids_tpu.exec.transitions import (
        CpuCoalesceBatchesExec,
        DeviceToHostExec,
        HostToDeviceExec,
        TpuCoalesceBatchesExec,
    )
    from spark_rapids_tpu.shuffle.exchange import (
        HashPartitioning,
        RangePartitioning,
        _ExchangeBase,
    )

    name = node.node_name()
    # -- output well-formedness ----------------------------------------------
    try:
        output = node.output
    except Exception as e:  # noqa: BLE001
        out.append(f"{name}: output schema is not computable: {e!r}")
        return
    for a in output:
        if not isinstance(a, AttributeReference):
            out.append(f"{name}: output element {a!r} is not an "
                       "AttributeReference")
            return
        if not isinstance(a.data_type, DataType) and \
                not hasattr(a.data_type, "to_np"):
            out.append(f"{name}: output column {a.name} has no usable "
                       f"dtype ({a.data_type!r})")

    available = _attr_map(a for c in node.children for a in c.output)

    from spark_rapids_tpu.aqe.loop import TpuAdaptiveExec
    from spark_rapids_tpu.aqe.stages import (
        TpuQueryStageExec,
        TpuStageReaderExec,
    )
    from spark_rapids_tpu.plan.spmd import TpuSpmdStageExec

    # -- per-class structure/reference checks --------------------------------
    if isinstance(node, TpuSpmdStageExec):
        # the wrapper is schema-transparent over its host-loop subtree
        # (which is verified member-by-member on its own walk); a missing
        # lowering record means execute() could never build the program
        _check_identity_schema(node, out)
        if node.info is None:
            out.append(f"{name}: SPMD stage carries no lowering info")
        # placement-consistency: an SPMD chain compiles to ONE device
        # program — a host-placed compute operator or a download edge
        # inside its subtree means a placement boundary STRADDLES the
        # chain (the placement pass re-places chains wholesale; a plan
        # that splits one is corrupt)
        from spark_rapids_tpu.exec.aggregate import CpuHashAggregateExec
        from spark_rapids_tpu.exec.cache import CpuCachedScanExec
        from spark_rapids_tpu.exec.expand import (
            CpuExpandExec,
            CpuGenerateExec,
        )
        from spark_rapids_tpu.exec.join import (
            CpuNestedLoopJoinExec,
            CpuShuffledHashJoinExec,
        )
        from spark_rapids_tpu.exec.sort import CpuSortExec
        from spark_rapids_tpu.exec.window import CpuWindowExec
        from spark_rapids_tpu.shuffle.exchange import CpuShuffleExchangeExec

        host_compute = (B.CpuProjectExec, B.CpuFilterExec, B.CpuUnionExec,
                        B.CpuLocalLimitExec, B.CpuGlobalLimitExec,
                        CpuHashAggregateExec,
                        CpuSortExec, CpuWindowExec, CpuShuffleExchangeExec,
                        CpuShuffledHashJoinExec, CpuNestedLoopJoinExec,
                        CpuExpandExec, CpuGenerateExec, CpuCachedScanExec,
                        DeviceToHostExec)
        for s in node.children[0].collect_nodes(
                lambda n: isinstance(n, host_compute)):
            out.append(
                f"{name}: SPMD chain straddles a placement boundary — "
                f"{s.node_name()} is host-placed inside a single-program "
                "device stage")
    elif isinstance(node, TpuAdaptiveExec):
        # schema/placement-transparent adaptive wrapper (aqe/loop.py)
        _check_identity_schema(node, out)
    elif isinstance(node, TpuQueryStageExec):
        # a materialized exchange boundary: a leaf whose schema is the
        # exchange's; its spec-consuming reader (below) does the rest
        pass
    elif isinstance(node, TpuStageReaderExec):
        # row-preserving partition-spec reader over a materialized stage
        _check_identity_schema(node, out)
        if not node.spec:
            out.append(f"{name}: empty partition spec — the reader would "
                       "produce zero partitions and drop every row")
        else:
            stage = node.children[0]
            if isinstance(stage, TpuQueryStageExec):
                _check_reader_spec(name, node.spec, stage, out)
    elif isinstance(node, TpuFusedStageExec):
        _check_fused_stage(node, out)
    elif isinstance(node, (B.TpuProjectExec, B.CpuProjectExec)):
        if len(output) != len(node.project_list):
            out.append(f"{name}: declares {len(output)} output columns "
                       f"for {len(node.project_list)} projections")
        _check_refs(name, node.project_list, available, out, "projection")
        for a, e in zip(output, node.project_list):
            dt = _expr_dtype(e)
            if dt is not None and a.data_type != dt:
                out.append(f"{name}: output column {a.name} declares "
                           f"{a.data_type} but its projection evaluates "
                           f"to {dt}")
    elif isinstance(node, (B.TpuFilterExec, B.CpuFilterExec)):
        _check_refs(name, [node.condition], available, out, "condition")
        dt = _expr_dtype(node.condition)
        if dt is not None and dt is not DataType.BOOL:
            out.append(f"{name}: filter condition evaluates to {dt}, "
                       "not BOOL")
        _check_identity_schema(node, out)
    elif isinstance(node, _ExpandBase):
        for pi, proj in enumerate(node.projections):
            if len(proj) != len(node.output_attrs):
                out.append(f"{name}: projection {pi} has {len(proj)} "
                           f"expressions for {len(node.output_attrs)} "
                           "output columns")
                continue
            _check_refs(name, proj, available, out, f"projection {pi}")
            for a, e in zip(node.output_attrs, proj):
                dt = _expr_dtype(e)
                if dt is not None and dt is not DataType.NULL and \
                        a.data_type != dt:
                    out.append(f"{name}: projection {pi} column {a.name} "
                               f"declares {a.data_type} but evaluates to "
                               f"{dt}")
    elif isinstance(node, _SortBase):
        _check_refs(name, [o.child for o in node.orders], available, out,
                    "sort key")
        _check_identity_schema(node, out)
    elif isinstance(node, _ExchangeBase):
        p = node.partitioning
        if isinstance(p, HashPartitioning):
            _check_refs(name, p.exprs, available, out, "partition key")
        elif isinstance(p, RangePartitioning):
            _check_refs(name, [o.child for o in p.orders], available, out,
                        "range key")
        _check_identity_schema(node, out)
    elif isinstance(node, _JoinBase):
        left = _attr_map(node.children[0].output)
        right = _attr_map(node.children[1].output)
        _check_refs(name, getattr(node, "left_keys", []) or [], left, out,
                    "left key")
        _check_refs(name, getattr(node, "right_keys", []) or [], right,
                    out, "right key")
        if getattr(node, "condition", None) is not None:
            _check_refs(name, [node.condition], available, out,
                        "join condition")
    elif isinstance(node, _HashAggregateBase):
        _check_refs(name, [g for g in node.grouping
                           if isinstance(g, AttributeReference)],
                    available, out, "grouping key")
    elif isinstance(node, (B.TpuLocalLimitExec, B.CpuLocalLimitExec,
                           B._GlobalLimitBase, B.CoalescePartitionsExec,
                           TpuCoalesceBatchesExec, CpuCoalesceBatchesExec,
                           HostToDeviceExec, DeviceToHostExec)):
        _check_identity_schema(node, out)
    elif isinstance(node, B._UnionBase):
        first = node.children[0].output
        for ci, c in enumerate(node.children[1:], start=1):
            if [a.data_type for a in c.output] != \
                    [a.data_type for a in first]:
                out.append(f"{name}: union input {ci} schema "
                           f"{_schema_str(c.output)} does not match input "
                           f"0 {_schema_str(first)}")

    # -- encoded scan claims (columnar/encoded.py) ---------------------------
    from spark_rapids_tpu.io.scan import _FileScanBase

    if isinstance(node, _FileScanBase):
        cached = getattr(node, "_encoded_plan_cache", None)
        if cached is not None and cached[1]:
            out_by_name = {a.name: a for a in output}
            if node.placement != "tpu":
                out.append(
                    f"{name}: claims encoded (dictionary) output columns "
                    "but is not a device scan — host batches cannot carry "
                    "DictionaryColumn")
            for cname in cached[1]:
                a = out_by_name.get(cname)
                if a is None:
                    out.append(
                        f"{name}: encoded-column claim {cname!r} names a "
                        "column the scan does not output")
                elif a.data_type not in (DataType.STRING, DataType.INT64,
                                         DataType.DATE,
                                         DataType.TIMESTAMP):
                    out.append(
                        f"{name}: encoded-column claim {cname!r} has dtype "
                        f"{a.data_type} — only STRING and fixed "
                        "INT64/DATE/TIMESTAMP columns have a "
                        "dictionary-code representation")

    # -- placement edges (every device<->host edge needs a transition) -------
    from spark_rapids_tpu.plan.transition_overrides import (
        _effective_placement,
    )

    my_p = _effective_placement(node)
    for c in node.children:
        child_p = _effective_placement(c)
        if my_p == "tpu" and child_p == "cpu" and \
                not isinstance(node, HostToDeviceExec):
            out.append(f"{name}: device operator consumes host batches "
                       f"from {c.node_name()} without a HostToDeviceExec")
        elif my_p == "cpu" and child_p == "tpu" and \
                not isinstance(node, DeviceToHostExec):
            out.append(f"{name}: host operator consumes device batches "
                       f"from {c.node_name()} without a DeviceToHostExec")

    # -- placement-boundary shape (one transition per boundary) --------------
    if isinstance(node, (HostToDeviceExec, DeviceToHostExec)):
        child = node.children[0]
        if isinstance(child, (HostToDeviceExec, DeviceToHostExec)):
            out.append(
                f"{name}: a placement boundary must carry exactly one "
                f"transition node, but {child.node_name()} is stacked "
                "directly beneath (the transition optimizer fuses "
                "inverse pairs — a surviving stack is a corrupt "
                "mixed plan)")
        elif isinstance(node, HostToDeviceExec) and \
                _effective_placement(child) == "tpu":
            out.append(
                f"{name}: upload transition over device-resident input "
                f"{child.node_name()} — no placement boundary here")
        elif isinstance(node, DeviceToHostExec) and \
                _effective_placement(child) == "cpu":
            out.append(
                f"{name}: download transition over host-resident input "
                f"{child.node_name()} — no placement boundary here")


def _check_reader_spec(name: str, spec, stage, out: List[str]) -> None:
    """Coverage/consistency of an adaptive reader's partition spec: every
    stage bucket must be consumed (a dropped bucket silently drops rows),
    a bucket may appear in at most ONE kind of entry, grouped buckets
    appear exactly once, and a bucket's piece slices must partition
    [0, n_pieces) without gaps or overlap. 'full' entries may repeat —
    that is the replicated build side opposite skew slices."""
    n_buckets = stage.pb.num_partitions
    kinds: Dict[int, str] = {}
    group_seen: Dict[int, int] = {}
    slices: Dict[int, List] = {}
    for e in spec:
        ts = e[1] if e[0] == "group" else [e[1]]
        for t in ts:
            if not (0 <= t < n_buckets):
                out.append(f"{name}: spec references bucket {t} of a "
                           f"{n_buckets}-bucket stage")
                return
            prev = kinds.get(t)
            if prev is not None and prev != e[0]:
                out.append(f"{name}: bucket {t} appears in both "
                           f"'{prev}' and '{e[0]}' spec entries")
            kinds[t] = e[0]
        if e[0] == "group":
            for t in ts:
                group_seen[t] = group_seen.get(t, 0) + 1
        elif e[0] == "slice":
            slices.setdefault(e[1], []).append((e[2], e[3]))
    missing = [t for t in range(n_buckets) if t not in kinds]
    if missing:
        out.append(f"{name}: spec consumes no entry for bucket(s) "
                   f"{missing} — their rows would be dropped")
    for t, cnt in group_seen.items():
        if cnt > 1:
            out.append(f"{name}: grouped bucket {t} appears {cnt} times "
                       "— its rows would be duplicated")
    stats = stage.stats
    for t, rs in slices.items():
        rs.sort()
        pos = 0
        for lo, hi in rs:
            if lo != pos or hi <= lo:
                out.append(f"{name}: bucket {t} slices {rs} do not "
                           "partition the piece range (gap/overlap)")
                break
            pos = hi
        else:
            if stats is not None and t < len(stats.piece_costs) and \
                    pos != len(stats.piece_costs[t]):
                out.append(f"{name}: bucket {t} slices end at piece "
                           f"{pos} but the bucket holds "
                           f"{len(stats.piece_costs[t])} pieces")


def _check_fused_stage(node, out: List[str]) -> None:
    """Fused-stage accounting: the stage's claimed operator count, member
    chain, and input node must agree, every member must be a fusable
    kind, and the member chain's recomputed running schema must reach the
    stage's declared output."""
    from spark_rapids_tpu.exec import basic as B
    from spark_rapids_tpu.exec.aggregate import (
        COMPLETE,
        PARTIAL,
        TpuHashAggregateExec,
    )
    from spark_rapids_tpu.exec.expand import TpuExpandExec
    from spark_rapids_tpu.exec.fused import is_fusable_scan_node
    from spark_rapids_tpu.plan.fusion import _agg_chain_member

    name = node.node_name()
    if len(node.members) != node.n_ops:
        out.append(f"{name}: claims {node.n_ops} fused operators but "
                   f"walked {len(node.members)} members")
        return
    cur: Optional[PhysicalExec] = node.children[0]
    for _ in range(node.n_ops):
        cur = cur.children[0] if cur is not None and cur.children else None
    if cur is not node.input_node:
        out.append(f"{name}: stage input accounting is wrong — the node "
                   f"{node.n_ops} below the top is not the recorded "
                   "stage input")
        return
    if node.agg_form:
        top = node.members[0]
        if not isinstance(top, TpuHashAggregateExec) or \
                top.mode not in (PARTIAL, COMPLETE):
            out.append(f"{name}: aggregate-form stage is not headed by a "
                       "partial/complete TpuHashAggregate")
        for m in node.members[1:]:
            if not _agg_chain_member(m):
                out.append(f"{name}: aggregate-form member "
                           f"{type(m).__name__} is not a fusable "
                           "update-chain operator")
        return
    # scan form: re-derive the running schema bottom-up exactly the way
    # execution composes the stage program (exec/fused._build_scan_ops)
    attrs = list(node.input_node.output)
    for m in reversed(node.members):
        if not is_fusable_scan_node(m):
            out.append(f"{name}: member {type(m).__name__} is not a "
                       "fusable pipelined operator")
            return
        available = _attr_map(attrs)
        mname = f"{name} member {type(m).__name__}"
        if isinstance(m, B.TpuProjectExec):
            _check_refs(mname, m.project_list, available, out,
                        "projection")
            attrs = m.output
        elif isinstance(m, TpuExpandExec):
            for proj in m.projections:
                _check_refs(mname, proj, available, out, "projection")
            attrs = list(m.output_attrs)
        elif isinstance(m, B.TpuFilterExec):
            _check_refs(mname, [m.condition], available, out, "condition")
    if [a.expr_id for a in attrs] != [a.expr_id for a in node.output] or \
            [a.data_type for a in attrs] != \
            [a.data_type for a in node.output]:
        out.append(f"{name}: member chain produces {_schema_str(attrs)} "
                   f"but the stage declares {_schema_str(node.output)}")


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def verify_plan(plan: PhysicalExec) -> List[PlanViolation]:
    """Bottom-up verification; returns violation records (empty = OK)."""
    from spark_rapids_tpu.exec.fused import TpuFusedStageExec

    out: List[str] = []
    stage_ids: Dict[int, int] = {}

    def walk(node: PhysicalExec) -> None:
        for c in node.children:
            walk(c)
        _check_node(node, out)
        if isinstance(node, TpuFusedStageExec):
            stage_ids[node.stage_id] = stage_ids.get(node.stage_id, 0) + 1

    walk(plan)
    for sid, n in sorted(stage_ids.items()):
        if n > 1:
            out.append(f"fused stage id {sid} appears {n} times — stage "
                       "accounting/EXPLAIN markers would collide")
    return [v if isinstance(v, PlanViolation) else PlanViolation(v)
            for v in out]


def check_plan(plan: PhysicalExec, conf) -> List[str]:
    """Verify and, per conf, raise. Returns the violations either way."""
    from spark_rapids_tpu import conf as C

    violations = verify_plan(plan)
    if violations and conf.get(C.PLAN_VERIFY_FAIL):
        raise PlanVerificationError(violations)
    return violations
